"""Conv probe round 3 (r5): find WHERE the conv backward loses 100x, and
measure the candidate fix — gradient convs re-expressed as plain
NHWC+HWIO forward convs with explicit operand transposes.

Timing hardened vs probe2 (whose small-window slopes went negative under
tunnel jitter): median of 5 slope trials at lo=4 / hi=12 chained calls,
each window readback-barriered; per-trial slopes printed so outliers are
visible.

Run on the real chip: ``python tools/tpu_conv_probe3.py``.
"""

import statistics
import sys
import time

import numpy as np


def _slope(f, lo=4, hi=12, trials=5):
    import jax
    f()
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(f())[0]))
    slopes = []
    for _ in range(trials):
        ts = []
        for k in (lo, hi):
            t0 = time.perf_counter()
            r = None
            for _ in range(k):
                r = f()
            np.asarray(jax.device_get(jax.tree_util.tree_leaves(r)[0]))
            ts.append(time.perf_counter() - t0)
        slopes.append((ts[1] - ts[0]) / (hi - lo))
    return statistics.median(slopes), slopes


def main():
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    print("device:", dev, getattr(dev, "device_kind", ""))

    # ResNet hot shape, stride 1: x [32,56,56,256], w [3,3,256,256]
    N, H, W, C, O, KH = 32, 56, 56, 256, 256, 3
    fl1 = 2 * N * H * W * C * O * KH * KH
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N, H, W, C)) * 0.05,
                    jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((KH, KH, C, O)) * 0.05,
                    jnp.bfloat16)
    dy = jnp.asarray(rng.standard_normal((N, H, W, O)) * 0.05,
                     jnp.bfloat16)
    dn = lambda l, r, spec: jax.lax.conv_dimension_numbers(l, r, spec)

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=dn(x.shape, w.shape,
                                 ("NHWC", "HWIO", "NHWC")))

    def report(name, med, slopes, flops):
        ss = " ".join(f"{s * 1e3:.1f}" for s in slopes)
        print(f"{name}: {med * 1e3:.2f} ms ({flops / med / 1e12:.1f} "
              f"TF/s) slopes[ms]=[{ss}]")

    # 1. forward conv (the reference point)
    cf = jax.jit(conv)
    med, sl = _slope(lambda: cf(x, w))
    report("fwd conv", med, sl, fl1)

    # 2. autodiff dgrad + wgrad (what the engine runs today)
    g = jax.jit(jax.grad(
        lambda x, w: conv(x, w).astype(jnp.float32).sum(), argnums=(0, 1)))
    med, sl = _slope(lambda: g(x, w))
    report("autodiff dgrad+wgrad", med, sl, 2 * fl1)

    gx = jax.jit(jax.grad(
        lambda x: conv(x, w).astype(jnp.float32).sum()))
    med, sl = _slope(lambda: gx(x))
    report("autodiff dgrad only", med, sl, fl1)

    gw = jax.jit(jax.grad(
        lambda w: conv(x, w).astype(jnp.float32).sum()))
    med, sl = _slope(lambda: gw(w))
    report("autodiff wgrad only", med, sl, fl1)

    # 3. dgrad as a PLAIN NHWC+HWIO conv: dx = conv(dy, flip(w)^T)
    def dgrad_plain(dy, w):
        wt = jnp.flip(w, (0, 1)).swapaxes(2, 3)   # [kh,kw,O,I] still HWIO
        return jax.lax.conv_general_dilated(
            dy, wt, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=dn(dy.shape, wt.shape,
                                 ("NHWC", "HWIO", "NHWC")))
    f3 = jax.jit(dgrad_plain)
    ref = jax.device_get(gx(x)).astype(np.float32)
    got = jax.device_get(f3(dy, w)).astype(np.float32)
    med, sl = _slope(lambda: f3(dy, w))
    report("dgrad plain-conv", med, sl, fl1)

    # numeric check vs autodiff (same dy: grad used dy=ones via sum; redo
    # with explicit vjp for a fair check)
    _, vjp = jax.vjp(lambda x: conv(x, w), x)
    want = jax.device_get(vjp(dy)[0]).astype(np.float32)
    err = np.max(np.abs(got - want)) / (np.max(np.abs(want)) + 1e-9)
    print(f"dgrad plain-conv rel err vs autodiff: {err:.2e}")

    # 4. wgrad as a PLAIN conv: dw[kh,kw,i,o] via lhs=x^T, rhs=dy^T
    def wgrad_plain(x, dy):
        lhs = jnp.transpose(x, (3, 1, 2, 0))      # [I, H, W, N]
        rhs = jnp.transpose(dy, (1, 2, 0, 3))     # [Ho, Wo, N, O]
        out = jax.lax.conv_general_dilated(
            lhs, rhs, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=dn(lhs.shape, rhs.shape,
                                 ("NHWC", "HWIO", "NHWC")))
        # out: [I, kh, kw, O] -> HWIO
        return jnp.transpose(out, (1, 2, 0, 3))
    f4 = jax.jit(wgrad_plain)
    _, vjpw = jax.vjp(lambda w: conv(x, w), w)
    want_w = jax.device_get(vjpw(dy)[0]).astype(np.float32)
    got_w = jax.device_get(f4(x, dy)).astype(np.float32)
    errw = np.max(np.abs(got_w - want_w)) / (np.max(np.abs(want_w)) + 1e-9)
    med, sl = _slope(lambda: f4(x, dy))
    report("wgrad plain-conv", med, sl, fl1)
    print(f"wgrad plain-conv rel err vs autodiff: {errw:.2e}")

    # 5. strided case (ResNet downsample): x [32,56,56,128] w [3,3,128,256]
    #    stride 2 — the dgrad needs lhs_dilation; measure both forms
    x2 = jnp.asarray(rng.standard_normal((N, H, W, 128)) * 0.05,
                     jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((KH, KH, 128, O)) * 0.05,
                     jnp.bfloat16)

    def conv_s2(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (2, 2), [(1, 1), (1, 1)],
            dimension_numbers=dn(x.shape, w.shape,
                                 ("NHWC", "HWIO", "NHWC")))
    fl2 = 2 * N * (H // 2) * (W // 2) * 128 * O * KH * KH
    g2 = jax.jit(jax.grad(
        lambda x, w: conv_s2(x, w).astype(jnp.float32).sum(),
        argnums=(0, 1)))
    med, sl = _slope(lambda: g2(x2, w2))
    report("autodiff dgrad+wgrad s2", med, sl, 2 * fl2)

    dy2 = jnp.asarray(rng.standard_normal((N, H // 2, W // 2, O)) * 0.05,
                      jnp.bfloat16)

    def dgrad_plain_s2(dy, w):
        wt = jnp.flip(w, (0, 1)).swapaxes(2, 3)
        # transposed-conv padding: lo = k-1-pad = 1; hi chosen so the
        # output recovers the full input extent (56 = 55 + 1 + 2 - 3 + 1)
        return jax.lax.conv_general_dilated(
            dy, wt, (1, 1), [(1, 2), (1, 2)], lhs_dilation=(2, 2),
            dimension_numbers=dn(dy.shape, wt.shape,
                                 ("NHWC", "HWIO", "NHWC")))
    f5 = jax.jit(dgrad_plain_s2)
    _, vjp2 = jax.vjp(lambda x: conv_s2(x, w2), x2)
    want2 = jax.device_get(vjp2(dy2)[0]).astype(np.float32)
    got2 = jax.device_get(f5(dy2, w2)).astype(np.float32)
    err2 = np.max(np.abs(got2 - want2)) / (np.max(np.abs(want2)) + 1e-9)
    med, sl = _slope(lambda: f5(dy2, w2))
    report("dgrad plain-conv s2", med, sl, fl2)
    print(f"dgrad plain-conv s2 rel err: {err2:.2e}")

    def wgrad_plain_s2(x, dy):
        lhs = jnp.transpose(x, (3, 1, 2, 0))
        rhs = jnp.transpose(dy, (1, 2, 0, 3))
        # wgrad padding: lo = fwd pad = 1; hi = (out-1)*s + k - in - lo
        # = 27*2 + 3 - 56 - 1 = 0
        out = jax.lax.conv_general_dilated(
            lhs, rhs, (1, 1), [(1, 0), (1, 0)], rhs_dilation=(2, 2),
            dimension_numbers=dn(lhs.shape, rhs.shape,
                                 ("NHWC", "HWIO", "NHWC")))
        return jnp.transpose(out, (1, 2, 0, 3))
    f6 = jax.jit(wgrad_plain_s2)
    _, vjpw2 = jax.vjp(lambda w: conv_s2(x2, w), w2)
    wantw2 = jax.device_get(vjpw2(dy2)[0]).astype(np.float32)
    gotw2 = jax.device_get(f6(x2, dy2)).astype(np.float32)
    errw2 = (np.max(np.abs(gotw2 - wantw2)) /
             (np.max(np.abs(wantw2)) + 1e-9))
    med, sl = _slope(lambda: f6(x2, dy2))
    report("wgrad plain-conv s2", med, sl, fl2)
    print(f"wgrad plain-conv s2 rel err: {errw2:.2e}")


if __name__ == "__main__":
    sys.exit(main())
