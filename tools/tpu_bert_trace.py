"""Trace ONE BERT-base engine step on chip and print the top XLA ops by
device time (r5: find where the non-MXU 60% goes at 40.1% MFU).
``python tools/tpu_bert_trace.py [batch]``."""

import collections
import gzip
import json
import pathlib
import sys
import tempfile

import numpy as np


def main():
    import jax
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    from paddle1_tpu.text.models import (BertForPretraining,
                                         BertPretrainingCriterion,
                                         bert_base)
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    seq = 128
    model = BertForPretraining(bert_base(
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    crit = BertPretrainingCriterion(model.bert.vocab_size)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(m, b):
        scores, rel = m(Tensor(b["ids"]))
        return crit(scores, rel, Tensor(b["mlm"]), Tensor(b["nsp"]))

    eng = ParallelEngine(model, opt, loss_fn,
                         mesh=build_mesh(dp=1, devices=[jax.devices()[0]]),
                         amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    v = model.bert.vocab_size
    b = eng.shard_batch(
        {"ids": rng.integers(1, v, (batch, seq)).astype(np.int32),
         "mlm": rng.integers(0, v, (batch, seq)).astype(np.int32),
         "nsp": rng.integers(0, 2, (batch,)).astype(np.int32)})
    for _ in range(3):  # compile + warm
        r = eng.step(b)
    np.asarray(jax.device_get(r.data if hasattr(r, "data") else r))

    td = tempfile.mkdtemp(prefix="bert_trace_")
    with jax.profiler.trace(td):
        r = eng.step(b)
        np.asarray(jax.device_get(r.data if hasattr(r, "data") else r))
    gz = list(pathlib.Path(td).rglob("*.trace.json.gz"))
    if not gz:
        print("no trace.json.gz produced under", td)
        return 1
    with gzip.open(gz[0]) as f:
        tr = json.load(f)
    ev = tr["traceEvents"]
    pids, tids = {}, {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"].get("name")
    dur, cnt = collections.Counter(), collections.Counter()
    for e in ev:
        if (e.get("ph") == "X"
                and "TPU" in str(pids.get(e["pid"], ""))
                and tids.get((e["pid"], e["tid"])) == "XLA Ops"):
            dur[e["name"]] += e.get("dur", 0)
            cnt[e["name"]] += 1
    tot = sum(dur.values())
    print(f"total XLA-op device time: {tot / 1e3:.2f} ms "
          f"({len(dur)} distinct ops)")
    for name, d in dur.most_common(30):
        print(f"{d / 1e3:8.3f} ms {100.0 * d / tot:5.1f}% "
              f"{cnt[name]:4d}x  {name[:90]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
