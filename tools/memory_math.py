"""Published memory math for BASELINE config 4 (ERNIE-1.5B on v5e).

Answers VERDICT r3 weak #5: can full-depth ernie_1p5b (1.637B params)
train on ONE v5e (16 GiB HBM) under the bench's regime (bf16 compute,
f32 Adam masters, per-block remat)? Run:  python tools/memory_math.py

Accounting per trainable param count N (the engine's actual residency):
  * f32 master params            4 N   (ParallelEngine inputs)
  * f32 Adam moments (m, v)      8 N   (optimizer slots)
  * f32 grads                    4 N   (transient; param-layout pinned)
  * bf16 compute param copy      2 N   (amp cast inside the step)
  * activations under remat      ~L*2*B*S*H bf16 boundaries + one
                                 block's recompute peak

Conclusion (printed): 24 layers needs ~28 GiB => does NOT fit a single
v5e; the largest depth that fits with margin is 10 layers (~13 GiB).
Config 4's single-chip number is therefore an L=10 depth-proxy with the
per-layer compute identical to full scale (same H/I/heads); full depth
runs sharded (ZeRO-2 over >= 4 chips — engine path validated on the
virtual 8-device mesh by dryrun_multichip / test_sharding_remat).
"""

GiB = 1024 ** 3


def ernie_params(layers, H=2304, I=9216, V=40000, P=2048):
    lp = (4 * H * H + 4 * H) + (H * I + I + I * H + H) + 4 * H
    emb = V * H + P * H + 2 * H + 2 * H
    pooler = H * H + H
    head = H * H + H + V + 2 * H  # decoder ties the word embedding
    nsp = H * 2 + 2
    return emb + layers * lp + pooler + head + nsp


def budget(layers, batch=4, seq=512, H=2304, I=9216):
    n = ernie_params(layers, H=H, I=I)
    static = 12 * n                      # master + adam moments, f32
    grads = 4 * n
    bf16 = 2 * n
    act = layers * batch * seq * H * 2 * 2 + batch * seq * I * 2 * 6
    return n, static, grads, bf16, act, static + grads + bf16 + act


def main():
    print(f"{'L':>3} {'params':>8} {'static':>8} {'grads':>7} "
          f"{'bf16':>6} {'acts':>6} {'peak':>8}  fits 16GiB v5e?")
    for layers in (24, 12, 10, 8, 6):
        n, st, g, b, a, tot = budget(layers)
        fits = "YES" if tot < 15 * GiB else "no"
        print(f"{layers:>3} {n / 1e9:>7.2f}B {st / GiB:>7.1f}G "
              f"{g / GiB:>6.1f}G {b / GiB:>5.1f}G {a / GiB:>5.2f}G "
              f"{tot / GiB:>7.1f}G  {fits}")
    n24 = ernie_params(24)
    for chips in (2, 4, 8):
        # ZeRO-2: moments+grads shard over chips; master params + bf16
        # copy stay replicated (stage 2)
        per = (4 * n24 + 2 * n24) + (8 * n24 + 4 * n24) / chips + \
            budget(24)[4]
        print(f"ZeRO-2 over {chips} chips: ~{per / GiB:.1f} GiB/chip"
              + ("  <- fits" if per < 15 * GiB else ""))


if __name__ == "__main__":
    main()
