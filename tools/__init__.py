# tools/ is a package so `python -m tools.lint` works from the repo
# root (the unified static-analysis entry — see tools/lint/).
