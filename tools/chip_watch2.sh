#!/bin/bash
# Round-5 tunnel watcher: probe until ~7h from launch; on recovery run
# the FULL queued chip sequence (VERDICT r4 items 1-2 + the r5 busbw
# harness) and save everything under chip_results/. One pass, then exit.
cd /root/repo
mkdir -p chip_results
LOG=chip_results/watch.log
echo "chip_watch2 start $(date -u)" >> "$LOG"
for i in $(seq 1 46); do
  if timeout 120 python -c "import jax, jax.numpy as jnp; jax.devices(); print(float(jnp.ones(8).sum()))" 2>/dev/null | grep -q "8.0"; then
    echo "tunnel ALIVE at $(date -u) (attempt $i)" >> "$LOG"
    echo "== kernel smoke ==" >> "$LOG"
    timeout 1800 python tools/tpu_kernel_smoke.py \
        > chip_results/kernel_smoke.txt 2>&1
    echo "kernel_smoke rc=$?" >> "$LOG"
    echo "== conv probe (incl. conv_nhwc flag) ==" >> "$LOG"
    timeout 2400 python tools/tpu_conv_probe.py \
        > chip_results/conv_probe.txt 2>&1
    echo "conv_probe rc=$?" >> "$LOG"
    echo "== bert batch sweep ==" >> "$LOG"
    for B in 32 64 128; do
      timeout 1800 python bench.py --batch $B \
          > "chip_results/bert_b$B.json" 2> "chip_results/bert_b$B.err"
      echo "bert b$B rc=$?" >> "$LOG"
    done
    echo "== configs 1/2/4/5 + busbw ==" >> "$LOG"
    for C in mnist_lenet resnet50_dp ernie_sharded yolov3_infer allreduce_busbw; do
      timeout 2400 python bench.py --config $C \
          > "chip_results/$C.json" 2> "chip_results/$C.err"
      echo "$C rc=$?" >> "$LOG"
    done
    echo "chip sequence DONE $(date -u)" >> "$LOG"
    exit 0
  fi
  echo "wedged attempt $i $(date -u)" >> "$LOG"
  sleep 540
done
echo "chip_watch2 gave up $(date -u)" >> "$LOG"
exit 1
