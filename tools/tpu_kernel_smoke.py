"""Real-chip Mosaic smoke for every Pallas kernel — CPU interpret mode
does not enforce Mosaic's tiling rules (the r3 flash-attention LSE bug
only surfaced on hardware), so this script compiles and numerically
checks each kernel on the actual TPU. Run: python tools/tpu_kernel_smoke.py"""

import sys

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    assert dev.platform == "tpu", f"need a TPU, got {dev.platform}"
    print("device:", getattr(dev, "device_kind", dev))
    rng = np.random.default_rng(0)
    failures = []

    def check(name, fn, ref, atol):
        try:
            got = np.asarray(jax.device_get(fn()))
            want = np.asarray(ref())
            err = float(np.max(np.abs(got - want)))
            ok = err <= atol
            print(f"{name:>18}: max_err={err:.2e} "
                  f"{'OK' if ok else f'FAIL (atol {atol})'}")
            if not ok:
                failures.append(name)
        except Exception as e:  # noqa: BLE001
            print(f"{name:>18}: EXCEPTION {type(e).__name__}: {e}")
            failures.append(name)

    # flash attention (mask + causal + grads)
    from paddle1_tpu.nn.functional.attention import attention_ref
    from paddle1_tpu.ops.pallas.flash_attention import flash_attention
    q, k, v = (jnp.asarray(rng.standard_normal((2, 256, 4, 64))
                           .astype(np.float32)) for _ in range(3))
    pm = jnp.asarray((rng.random((2, 256)) > 0.2).astype(np.float32))
    check("flash", lambda: jax.jit(flash_attention)(q, k, v),
          lambda: attention_ref(q, k, v), 5e-2)
    check("flash_causal",
          lambda: jax.jit(lambda q, k, v: flash_attention(
              q, k, v, causal=True))(q, k, v),
          lambda: attention_ref(q, k, v, is_causal=True), 5e-2)
    check("flash_masked",
          lambda: jax.jit(lambda q, k, v, pm: flash_attention(
              q, k, v, padding_mask=pm))(q, k, v, pm),
          lambda: attention_ref(q, k, v,
                                mask=(pm[:, None, None, :] > 0.5)), 5e-2)
    check("flash_grad",
          lambda: jax.jit(jax.grad(lambda q: flash_attention(
              q, k, v, padding_mask=pm).astype(jnp.float32).sum()))(q),
          lambda: jax.grad(lambda q: attention_ref(
              q, k, v, mask=(pm[:, None, None, :] > 0.5))
              .astype(jnp.float32).sum())(q), 8e-2)

    # flash BACKWARD kernels (this smoke passed on-chip in r5, so the
    # core flag flash_backward now defaults to 'auto')
    from paddle1_tpu.ops.pallas import flash_attention as fa_mod
    from paddle1_tpu.ops.pallas.flash_attention_bwd import \
        flash_attention_bwd
    dout = jnp.asarray(rng.standard_normal(q.shape).astype(np.float32))
    scale = 1.0 / (q.shape[-1] ** 0.5)

    def bwd_pair(causal, mask):
        out, lse = fa_mod._flash_fwd(q, k, v, scale, causal,
                                     padding_mask=mask)
        got = flash_attention_bwd(q, k, v, out, lse, dout, scale,
                                  causal, padding_mask=mask)
        want = fa_mod._bwd_xla(q, k, v, out, lse, dout, scale, causal,
                               padding_mask=mask)
        return got, want
    for nm, ca, mk in (("flash_bwd", False, None),
                       ("flash_bwd_causal", True, None),
                       ("flash_bwd_masked", False, pm)):
        got, want = bwd_pair(ca, mk)  # compute ONCE per config
        for which, g, w in zip(("dq", "dk", "dv"), got, want):
            check(f"{nm}.{which}", lambda g=g: g, lambda w=w: w, 8e-2)

    # fused layer norm
    from paddle1_tpu.ops.pallas.layer_norm import fused_layer_norm
    x = jnp.asarray(rng.standard_normal((512, 768)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((768,)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((768,)).astype(np.float32))

    def ln_ref():
        mu = x.mean(axis=-1, keepdims=True)
        var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * w + b
    check("layer_norm",
          lambda: jax.jit(fused_layer_norm)(x, w, b), ln_ref, 5e-3)

    # fused softmax
    from paddle1_tpu.ops.pallas.softmax import fused_softmax
    s = jnp.asarray(rng.standard_normal((384, 512)).astype(np.float32))
    check("softmax", lambda: jax.jit(fused_softmax)(s),
          lambda: jax.nn.softmax(s, axis=-1), 5e-4)

    # fused batch norm (train + eval, fp32 + bf16, +/- residual,
    # forward AND the one-pass backward kernels vs the XLA
    # compositions — the ISSUE 15 family; CPU interpret mode cannot
    # enforce Mosaic's tiling or the two-phase accumulator grid)
    from paddle1_tpu.core.flags import flags_guard
    from paddle1_tpu.ops.pallas import fused_bn as pbn
    from paddle1_tpu.ops.pallas import fused_bn_bwd as pbnb
    rows, c = 2048, 128
    xb = jnp.asarray((rng.standard_normal((rows, c)) * 2 + 1)
                     .astype(np.float32))
    gb = jnp.asarray(rng.standard_normal((c,)).astype(np.float32))
    bb = jnp.asarray(rng.standard_normal((c,)).astype(np.float32))
    resb = jnp.asarray(rng.standard_normal((rows, c))
                       .astype(np.float32))
    dyb = jnp.asarray(rng.standard_normal((rows, c)).astype(np.float32))
    bn_eps = 1e-5

    def bn_ref(x, res=None, act="relu"):
        m = x.mean(0)
        v = x.var(0)
        y = (x - m) / jnp.sqrt(v + bn_eps) * gb + bb
        if res is not None:
            y = y + res
        return jnp.maximum(y, 0.0) if act == "relu" else y

    check("bn_train",
          lambda: jax.jit(lambda x: pbn.fused_bn_train(
              x, gb, bb, bn_eps, act="relu")[0])(xb),
          lambda: bn_ref(xb), 5e-3)
    check("bn_train_res",
          lambda: jax.jit(lambda x, r: pbn.fused_bn_train(
              x, gb, bb, bn_eps, act="relu", residual=r)[0])(xb, resb),
          lambda: bn_ref(xb, resb), 5e-3)
    check("bn_train_bf16",
          lambda: jax.jit(lambda x: pbn.fused_bn_train(
              x, gb, bb, bn_eps)[0])(
              xb.astype(jnp.bfloat16)).astype(jnp.float32),
          lambda: bn_ref(xb.astype(jnp.bfloat16).astype(jnp.float32),
                         act="identity"), 5e-2)
    mstat = xb.mean(0)
    vstat = xb.var(0)
    check("bn_eval",
          lambda: jax.jit(lambda x: pbn.fused_bn_norm(
              x, mstat, vstat, gb, bb, bn_eps, act="relu"))(xb),
          lambda: bn_ref(xb), 5e-3)
    check("bn_local_moments",
          lambda: (lambda s, ss: s + ss)(*pbn.local_moments(xb)),
          lambda: xb.sum(0) + (xb * xb).sum(0), 5e-2)

    # backward kernels: the shared forward/setup runs INSIDE the
    # harness too — a Mosaic failure here must print a named FAIL and
    # let the remaining kernel families run, not abort the script
    try:
        y_act = pbn.fused_bn_train(xb, gb, bb, bn_eps, act="relu")[0]
        with flags_guard(fused_bn_bwd="always"):
            got_tb = jax.jit(lambda *a: pbnb.train_bwd(
                *a, bn_eps, "relu", with_res=True))(
                xb, gb, mstat, vstat, y_act, dyb)
            got_nb = jax.jit(lambda *a: pbnb.norm_bwd(
                *a, bn_eps, "relu"))(xb, gb, mstat, vstat, y_act, dyb)
    except Exception as e:  # noqa: BLE001
        print(f"      bn_bwd.setup: EXCEPTION {type(e).__name__}: {e}")
        failures.append("bn_bwd.setup")
    else:
        want_tb = pbnb.train_bwd_xla(xb, gb, mstat, vstat, y_act, dyb,
                                     bn_eps, "relu", with_res=True)
        for which, gg, ww in zip(("dx", "dgamma", "dbeta", "dres"),
                                 got_tb, want_tb):
            check(f"bn_bwd.{which}", lambda gg=gg: gg,
                  lambda ww=ww: ww, 5e-2)
        want_nb = pbnb.norm_bwd_xla(xb, gb, mstat, vstat, y_act, dyb,
                                    bn_eps, "relu")
        for which, gg, ww in zip(("dx", "dgamma", "dbeta"), got_nb,
                                 want_nb):
            check(f"bn_eval_bwd.{which}", lambda gg=gg: gg,
                  lambda ww=ww: ww, 5e-2)

    # fused adam
    from paddle1_tpu.ops.pallas.fused_adam import fused_adam_update
    n = 8192 * 2
    p = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    g = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    m1 = jnp.zeros(n, jnp.float32)
    m2 = jnp.zeros(n, jnp.float32)

    def adam_fused():
        return jax.jit(lambda p, g, m1, m2: fused_adam_update(
            p, g, m1, m2, 1e-3, 1, 0.9, 0.999, 1e-8, 0.01))(p, g, m1,
                                                            m2)[0]

    def adam_ref():
        nm1 = 0.1 * g
        nm2 = 0.001 * g * g
        upd = (nm1 / (1 - 0.9)) / (jnp.sqrt(nm2 / (1 - 0.999)) + 1e-8)
        return p * (1 - 1e-3 * 0.01) - 1e-3 * upd
    check("fused_adam", adam_fused, adam_ref, 1e-5)

    if failures:
        print("FAILURES:", failures)
        return 1
    print("ALL PALLAS KERNELS OK ON CHIP")
    return 0


if __name__ == "__main__":
    sys.exit(main())
