"""On-chip probe for the conv-throughput question (BASELINE.md "open
perf questions"): honest slope+readback timing of (a) raw convs in both
layouts, (b) one ResNet-50 engine step, (c) a profiler trace of that
step. Run on the real chip: ``python tools/tpu_conv_probe.py``."""

import sys
import time

import numpy as np


def _slope(f, lo=2, hi=8):
    import jax
    f()  # warm
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(f())[0]))
    ts = []
    for k in (lo, hi):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = f()
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(r)[0]))
        ts.append(time.perf_counter() - t0)
    return (ts[1] - ts[0]) / (hi - lo)


def main():
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    print("device:", dev, getattr(dev, "device_kind", ""))

    # raw conv, both layouts, bf16 — ResNet hot shape
    fl = 2 * 32 * 56 * 56 * 256 * 256 * 9
    x_nchw = jnp.asarray(np.random.randn(32, 256, 56, 56), jnp.bfloat16)
    w_oihw = jnp.asarray(np.random.randn(256, 256, 3, 3), jnp.bfloat16)
    conv_nchw = jax.jit(lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))))
    dt = _slope(lambda: conv_nchw(x_nchw, w_oihw))
    print(f"conv NCHW bf16: {dt * 1e3:.2f} ms {fl / dt / 1e12:.1f} TF/s")

    x_nhwc = jnp.asarray(np.random.randn(32, 56, 56, 256), jnp.bfloat16)
    w_hwio = jnp.asarray(np.random.randn(3, 3, 256, 256), jnp.bfloat16)
    conv_nhwc = jax.jit(lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))))
    dt = _slope(lambda: conv_nhwc(x_nhwc, w_hwio))
    print(f"conv NHWC bf16: {dt * 1e3:.2f} ms {fl / dt / 1e12:.1f} TF/s")

    # full ResNet-50 engine step + trace
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    from paddle1_tpu.vision.models.resnet import resnet50
    model = resnet50()
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())

    def loss_fn(m, b):
        return paddle.nn.functional.cross_entropy(m(Tensor(b["x"])),
                                                  Tensor(b["y"]))
    eng = ParallelEngine(model, opt, loss_fn,
                         mesh=build_mesh(dp=1, devices=[dev]),
                         amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    b = {"x": rng.standard_normal((32, 3, 224, 224)).astype(np.float32),
         "y": rng.integers(0, 1000, (32,)).astype(np.int64)}
    dt = _slope(lambda: eng.step(b), lo=1, hi=4)
    rflops = 3 * 32 * 4.1e9
    print(f"resnet50 step: {dt * 1e3:.1f} ms "
          f"{rflops / dt / 1e12:.1f} TF/s "
          f"mfu={rflops / dt / 197e12:.3f}")

    # the candidate fix: same engine step with NHWC-internal convs
    # (core flag conv_nhwc; boundary transposes cancel under XLA)
    from paddle1_tpu.core import flags as core_flags
    core_flags.set_flags({"conv_nhwc": "always"})
    try:
        model2 = resnet50()
        opt2 = paddle.optimizer.Momentum(learning_rate=0.1,
                                         parameters=model2.parameters())
        eng2 = ParallelEngine(model2, opt2, loss_fn,
                              mesh=build_mesh(dp=1, devices=[dev]),
                              amp_dtype="bfloat16")
        dt2 = _slope(lambda: eng2.step(b), lo=1, hi=4)
        print(f"resnet50 step (conv_nhwc=always): {dt2 * 1e3:.1f} ms "
              f"{rflops / dt2 / 1e12:.1f} TF/s "
              f"mfu={rflops / dt2 / 197e12:.3f}")
    finally:
        core_flags.set_flags({"conv_nhwc": "never"})

    import tempfile
    td = tempfile.mkdtemp(prefix="conv_probe_")
    with jax.profiler.trace(td):
        r = eng.step(b)
        np.asarray(jax.device_get(r.data if hasattr(r, "data") else r))
    print("trace:", td)


if __name__ == "__main__":
    sys.exit(main())
