"""Bench trajectory: persist every bench result, fail on regression.

Every ``bench.py`` invocation prints one JSON result line and the
number evaporates — the repo had BENCH_r0*.json snapshots from manual
rounds but nothing that accumulates run-over-run (ISSUE 13 satellite:
"the trajectory is currently empty"). This tool is the pipe fitting::

    set -o pipefail
    python bench.py --serving | python tools/bench_history.py append --compare

``append`` reads stdin, echoes every line through unchanged (the
driver's parsers keep working), validates result lines with
``bench.parse_result_line``, and appends them — stamped with a
timestamp and the git head — to ``BENCH_history.jsonl`` (override with
``--history``). ``--compare`` then exits nonzero when any metric
appended this run regressed against the BEST of its last 5 prior
recorded runs by more than the metric's noise band — a ratchet, not a
threshold: yesterday's best run is the bar, so a slow creep across
runs trips it even when each single step stays inside the band. The
band is ``max(10%, 3 * cv)`` where ``cv`` is the window's own
coefficient of variation (capped at 50%): cross-runner throughput
jitter widens its own tolerance instead of failing CI, while tight
metrics keep the 10% floor.

"Regressed" respects the metric's direction: throughput-style metrics
(samples/s, req/s, tok/s...) regress DOWN; overhead-style metrics
(``*_frac``, ``fraction`` unit) regress UP. ``vs_baseline`` gates
(the soaks that emit 1.0/0.0 contracts) are additionally checked:
a run whose ``vs_baseline`` dropped below 1.0 while history has it at
1.0 fails regardless of the raw value.

``compare`` alone re-checks the newest run already in the history
(no stdin), and ``show`` prints the last entries per metric.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:  # run as `python tools/bench_history.py`
    sys.path.insert(0, _ROOT)

from bench import parse_result_line  # noqa: E402

DEFAULT_HISTORY = os.path.join(_ROOT, "BENCH_history.jsonl")
# metrics where a SMALLER value is the better one
_LOWER_IS_BETTER_UNITS = {"fraction"}
_LOWER_IS_BETTER_SUFFIXES = ("_frac", "_fraction", "_overhead")
REGRESSION_FRAC = 0.10
COMPARE_WINDOW = 5
# noise band (ISSUE 14, the PR 13 accepted finding): raw-throughput
# ratchets ran on shared CI runners whose run-to-run spread exceeds a
# fixed 10%, so the tolerance is derived from the history's OWN
# coefficient of variation — a metric whose recorded window varies
# ±15% gets a ~3-sigma band (~45%), a tight metric keeps the 10%
# floor. Capped so a pathologically noisy history can never wave a
# real collapse through.
CV_SIGMA = 3.0
CV_TOLERANCE_CAP = 0.50


def noise_tolerance(vals: list) -> float:
    """Per-metric relative regression tolerance: the REGRESSION_FRAC
    floor widened to CV_SIGMA * (stdev/mean) of the compared window,
    capped at CV_TOLERANCE_CAP. Fewer than 3 samples (or a ~0 mean)
    keep the floor — one or two points carry no spread estimate."""
    if len(vals) < 3:
        return REGRESSION_FRAC
    mean = sum(vals) / len(vals)
    if abs(mean) < 1e-12:
        return REGRESSION_FRAC
    var = sum((v - mean) ** 2 for v in vals) / len(vals)
    cv = (var ** 0.5) / abs(mean)
    return max(REGRESSION_FRAC, min(CV_TOLERANCE_CAP, CV_SIGMA * cv))


def _git_head() -> str:
    try:
        return subprocess.run(
            ["git", "-C", _ROOT, "rev-parse", "--short", "HEAD"],
            capture_output=True, timeout=10,
        ).stdout.decode().strip() or "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def lower_is_better(rec: dict) -> bool:
    return (rec.get("unit") in _LOWER_IS_BETTER_UNITS
            or str(rec.get("metric", "")).endswith(
                _LOWER_IS_BETTER_SUFFIXES))


def read_history(path: str) -> list:
    out = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except ValueError:
                    continue  # a torn line must not kill the ratchet
    except OSError:
        pass
    return out


def append_records(path: str, recs: list) -> None:
    head = _git_head()
    now = round(time.time(), 3)
    with open(path, "a") as f:
        for rec in recs:
            row = {"ts": now, "git": head, "run_id": f"{head}@{now}"}
            row.update(rec)
            f.write(json.dumps(row) + "\n")


def check_regressions(history: list, fresh: list) -> list:
    """Compare each fresh record against the best of the last
    COMPARE_WINDOW prior entries of the same metric. Returns a list of
    human-readable regression messages (empty = green)."""
    problems = []
    for rec in fresh:
        name = rec["metric"]
        prior = [h for h in history if h.get("metric") == name]
        prior = prior[-COMPARE_WINDOW:]
        if not prior:
            continue  # first recorded run of this metric seeds the bar
        lower = lower_is_better(rec)
        vals = [float(h["value"]) for h in prior
                if isinstance(h.get("value"), (int, float))]
        if vals:
            best = min(vals) if lower else max(vals)
            v = float(rec["value"])
            tol = noise_tolerance(vals)
            if lower:
                # relative ratchet PLUS an absolute floor: overhead
                # fractions hover near 0 where 0.001 -> 0.002 is 2x
                # relative but pure scheduler noise — a point of real
                # overhead (0.01 absolute) is the signal worth failing
                regressed = (best >= 0
                             and v > best * (1 + tol)
                             and v - best > 0.01)
            else:
                regressed = v < best * (1 - tol)
            if regressed:
                problems.append(
                    f"{name}: {v:g} {rec.get('unit', '')} vs best-of-"
                    f"last-{len(vals)} {best:g} — "
                    f"{'up' if lower else 'down'} more than "
                    f"{tol:.0%}"
                    + (f" (noise band from window cv, floor "
                       f"{REGRESSION_FRAC:.0%})"
                       if tol > REGRESSION_FRAC else ""))
        # contract gates: the soaks emit vs_baseline as a BINARY
        # 1.0/0.0 verdict — only that shape is a contract (a
        # continuous ratio like bert's mfu/0.40 hovering around 1.0
        # must ride the value ratchet above, not hard-fail at 0.999)
        vb = rec.get("vs_baseline")
        prior_vb = [float(h.get("vs_baseline", 0)) for h in prior]
        if (isinstance(vb, (int, float)) and vb == 0.0
                and prior_vb and all(v in (0.0, 1.0) for v in prior_vb)
                and max(prior_vb) == 1.0):
            problems.append(
                f"{name}: vs_baseline dropped to 0.0 (history holds "
                "the 1.0 verdict) — the soak's contract broke")
    return problems


def cmd_append(args) -> int:
    fresh = []
    for line in sys.stdin:
        sys.stdout.write(line)  # transparent tee: parsers downstream
        sys.stdout.flush()      # keep seeing exactly bench's output
        ln = line.strip()
        if not (ln.startswith("{") and ln.endswith("}")):
            continue
        try:
            fresh.append(parse_result_line(ln))
        except (ValueError, KeyError):
            continue  # diagnostic JSON that is not a result line
    history = read_history(args.history)
    if fresh:
        append_records(args.history, fresh)
    if not args.compare:
        return 0
    return _report(check_regressions(history, fresh), args.history)


def cmd_compare(args) -> int:
    history = read_history(args.history)
    if not history:
        print(f"bench_history: {args.history} is empty — nothing to "
              "compare", file=sys.stderr)
        return 0
    last_run = history[-1].get("run_id")
    fresh = [h for h in history if h.get("run_id") == last_run]
    prior = [h for h in history if h.get("run_id") != last_run]
    return _report(check_regressions(prior, fresh), args.history)


def _report(problems: list, path: str) -> int:
    if problems:
        for p in problems:
            print(f"bench_history REGRESSION: {p}", file=sys.stderr)
        print(f"bench_history: {len(problems)} regression(s) vs "
              f"{path} (off the best of the last "
              f"{COMPARE_WINDOW} runs, beyond each metric's noise "
              "band)", file=sys.stderr)
        return 1
    return 0


def cmd_show(args) -> int:
    history = read_history(args.history)
    by_metric: dict = {}
    for h in history:
        by_metric.setdefault(h.get("metric", "?"), []).append(h)
    for name in sorted(by_metric):
        rows = by_metric[name][-args.n:]
        print(f"{name} ({rows[-1].get('unit', '')}):")
        for h in rows:
            print(f"  {h.get('git', '?'):>8} {h.get('value')}"
                  f" (vs_baseline {h.get('vs_baseline')})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("append", "compare", "show"))
    ap.add_argument("--history", default=DEFAULT_HISTORY,
                    help="the JSONL trajectory file "
                         "(default BENCH_history.jsonl at repo root)")
    ap.add_argument("--compare", action="store_true",
                    help="with `append`: after recording, exit 1 on a "
                         "regression beyond the metric's noise band "
                         "(max(10%%, 3*cv), cv from the window) vs "
                         "the best of the last 5 prior runs")
    ap.add_argument("-n", type=int, default=8,
                    help="with `show`: rows per metric")
    args = ap.parse_args(argv)
    if args.command == "append":
        return cmd_append(args)
    if args.command == "compare":
        return cmd_compare(args)
    return cmd_show(args)


if __name__ == "__main__":
    sys.exit(main())
