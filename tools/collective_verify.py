"""Cross-rank collective-schedule verifier CLI (ISSUE 14).

The ``tools.lint``-adjacent entry for the runtime half of the
SPMD-discipline suite: every process armed with
``FLAGS_debug_collective_sanitizer=1`` journals its collective
schedule as ``collective-<rank>.jsonl`` (see
``core/collective_sanitizer.py``); this tool replays the cross-rank
comparison the Supervisor runs at sweep time, plus the completion
check (a rank whose journal simply STOPS while peers continue is the
would-be deadlock)::

    python -m tools.collective_verify <journal-dir>            # full check
    python -m tools.collective_verify <journal-dir> --prefix   # live job

Exit 0 when every rank claims the same schedule, 1 on divergence (the
typed error text names the first diverging step and both ranks'
surrounding schedules), 2 when the directory holds fewer than two
rank journals (nothing to compare — probably the wrong dir, or the
flag was off: off writes no files at all).
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:  # run as `python tools/collective_verify.py`
    sys.path.insert(0, _ROOT)

from paddle1_tpu.core.collective_sanitizer import (  # noqa: E402
    CollectiveDivergenceError, journal_rank_count, verify_dir)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.collective_verify",
        description="cross-rank collective-schedule verification "
                    "(see core/collective_sanitizer.py)")
    ap.add_argument("journal_dir",
                    help="directory holding collective-<rank>.jsonl "
                         "journals (the Supervisor's log dir "
                         "'collective/' subdir, or "
                         "FLAGS_collective_journal_dir)")
    ap.add_argument("--prefix", action="store_true",
                    help="compare only the common prefix (a LIVE "
                         "job's ranks are legitimately at different "
                         "positions); default additionally fails "
                         "when one finished rank's schedule is a "
                         "strict prefix of another's")
    args = ap.parse_args(argv)
    nranks = journal_rank_count(args.journal_dir)
    if nranks < 2:
        print(f"collective_verify: {nranks} rank journal(s) under "
              f"{args.journal_dir!r} — need at least 2 to compare "
              "(is FLAGS_debug_collective_sanitizer on? off writes "
              "no files)", file=sys.stderr)
        return 2
    try:
        steps = verify_dir(args.journal_dir,
                           complete=not args.prefix)
    except CollectiveDivergenceError as e:
        print(f"collective_verify DIVERGENCE: {e}", file=sys.stderr)
        return 1
    print(f"collective_verify: {nranks} ranks agree on "
          f"{steps} collective step(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
