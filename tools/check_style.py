#!/usr/bin/env python
"""Source hygiene checks (reference tools/codestyle/ + check_file_diff_
approvals.sh role, scoped): line length, tabs, trailing whitespace,
accidental debug prints in the package, and that every test file is
collected by pytest's naming convention."""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LEN = 100


def check() -> int:
    bad = 0
    for root, dirs, files in os.walk(os.path.join(REPO, "paddle1_tpu")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, REPO)
            for i, line in enumerate(open(path), 1):
                stripped = line.rstrip("\n")
                if "\t" in stripped:
                    print(f"{rel}:{i}: tab character")
                    bad += 1
                if len(stripped) > MAX_LEN:
                    print(f"{rel}:{i}: line longer than {MAX_LEN}")
                    bad += 1
                if re.match(r"\s*import pdb|\s*pdb\.set_trace", stripped):
                    print(f"{rel}:{i}: pdb left in source")
                    bad += 1
    for fn in os.listdir(os.path.join(REPO, "tests")):
        if fn.endswith(".py") and fn not in ("conftest.py", "op_test.py") \
                and not fn.startswith("test_"):
            print(f"tests/{fn}: not collected (must start with test_)")
            bad += 1
    print(f"check_style: {'OK' if not bad else f'{bad} issue(s)'}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(check())
