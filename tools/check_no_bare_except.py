#!/usr/bin/env python
"""Shim: the bare-except lint moved into the unified suite (ISSUE 11).

The implementation (rules unchanged) lives in
``tools/lint/bare_except.py`` and runs as the ``bare-except`` pass of
``python -m tools.lint --all``. This file keeps the historical
standalone surface — ``check_source``, ``main``, the module constants —
for existing callers and tests, and still works as a script:
``python tools/check_no_bare_except.py [paths...]``.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.bare_except import (  # noqa: E402 — path bootstrap first
    BROAD_NAMES, DEFAULT_PATHS, ERROR_FORWARDING_FILES, MARKER,
    PREEMPTION_HANDLER_FILES, PREEMPTION_NAMES, check_source,
    iter_py_files, main)

__all__ = ["BROAD_NAMES", "DEFAULT_PATHS", "ERROR_FORWARDING_FILES",
           "MARKER", "PREEMPTION_HANDLER_FILES", "PREEMPTION_NAMES",
           "check_source", "iter_py_files", "main"]

if __name__ == "__main__":
    sys.exit(main())
