"""Repro/diagnosis for the MULTICHIP_r03 involuntary-remat warnings.

Builds the exact dryrun hybrid engine (dp2 x mp2 x zero2) on a virtual
8-device CPU mesh, compiles the train step, and greps the optimized HLO
for the offending f32[2,32,64] tensors so we can see which model value
they are. Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/remat_repro.py
"""
import os
import sys

# Force the virtual CPU mesh even under the axon sitecustomize hook: this
# diagnostic must never touch the TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import __graft_entry__ as g
import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.distributed import ParallelEngine, build_mesh
from paddle1_tpu.text.models import apply_megatron_sharding


def main():
    model, crit = g._tiny_bert()
    apply_megatron_sharding(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(m, batch):
        scores, rel = m(Tensor(batch["ids"]))
        return crit(scores, rel, Tensor(batch["mlm"]), Tensor(batch["nsp"]))

    degrees = {"dp": 2, "mp": 2, "sharding": 2}
    mesh = build_mesh(**degrees, devices=jax.devices()[:8])
    engine = ParallelEngine(model, opt, loss_fn, mesh=mesh, zero_stage=2,
                            clip_global_norm=1.0)
    batch = g._batch(512, 8, 32)
    placed = engine.shard_batch(batch)
    import jax.random as jrandom
    lowered = engine._jit.lower(engine.params, engine.opt_state, placed,
                                jrandom.PRNGKey(0), 1e-4)
    compiled = lowered.compile()
    hlo = compiled.as_text()
    lines = [ln for ln in hlo.splitlines() if "f32[2,32,64]" in ln]
    print(f"== {len(lines)} HLO lines mention f32[2,32,64] ==")
    for ln in lines:
        print(ln.strip()[:400])


if __name__ == "__main__":
    main()
