"""Measure the flash-vs-dense attention crossover on chip (r5): at
seq 128 XLA's dense attention beat the Pallas flash path by 1.5x at the
BERT-step level; find the sequence length where flash starts winning so
the dispatch can pick per-shape. Constant token count (b*s = 16384),
BERT-base head geometry, fwd+bwd via the public functional API.

``python tools/tpu_flash_crossover.py``
"""

import sys
import time

import numpy as np


def _min_time(f, k=6, trials=4):
    import jax
    np.asarray(jax.device_get(f()))
    best = None
    for _ in range(trials):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = f()
        np.asarray(jax.device_get(r))
        dt = (time.perf_counter() - t0) / k
        best = dt if best is None else min(best, dt)
    return best


def main():
    import jax
    import jax.numpy as jnp
    from paddle1_tpu.core.flags import flags_guard
    from paddle1_tpu.nn.functional.attention import \
        scaled_dot_product_attention as sdpa
    from paddle1_tpu.core.tensor import Tensor

    heads, d = 12, 64
    print("device:", jax.devices()[0])
    for b, s in [(128, 128), (64, 256), (32, 512), (16, 1024),
                 (8, 2048), (4, 4096)]:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, s, heads, d)),
                        jnp.bfloat16)
        # grad wrt q through the public functional path
        def make(mode):
            def loss(q):
                with flags_guard(flash_attention=mode,
                                 flash_backward=mode):
                    out = sdpa(Tensor(q), Tensor(q), Tensor(q),
                               is_causal=False)
                return jnp.sum(out.data.astype(jnp.float32))
            # scalar output only: downloading dq (25 MB) through the
            # relay would swamp the op time
            g = jax.jit(lambda q: jnp.sum(
                jax.grad(loss)(q).astype(jnp.float32)))
            return lambda: g(q)
        # fwd = 2 matmuls (qk^T, av) = 4*b*h*s^2*d FLOPs; bwd ~ 2x fwd
        fl = 4 * b * heads * s * s * d * 3
        t_flash = _min_time(make("always"))
        t_dense = _min_time(make("never"))
        w = "flash" if t_flash < t_dense else "dense"
        print(f"b={b:4d} s={s:5d}: flash {t_flash * 1e3:8.2f} ms "
              f"({fl / t_flash / 1e12:5.1f} TF/s)  dense "
              f"{t_dense * 1e3:8.2f} ms ({fl / t_dense / 1e12:5.1f} "
              f"TF/s)  -> {w}")


if __name__ == "__main__":
    sys.exit(main())
