"""Lint pass: the metric-name contract (ISSUE 10 satellite).

Migrated from ``tools/check_metric_names.py`` into the unified
framework — the standalone script is now a thin shim over this module.

AST-collects string-literal metric names at ``.counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` call sites across
``paddle1_tpu/`` (plus ``bench.py``/``bench_utils.py``) and enforces
what the Prometheus exposition (and the conformance test) depend on:

* **snake_case** — ``[a-z][a-z0-9_]*``;
* **counters end ``_total``** (the ``rate()`` convention), gauges and
  histograms must NOT;
* **histograms carry a unit suffix** — ``_seconds``/``_ms``/``_us``/
  ``_s``/``_per_s``/``_bytes``/``_ratio`` (or a known unitless
  family);
* **canonical unit spellings** (ISSUE 13, all kinds): ``_seconds``
  not ``_secs``/``_sec``/``_second``, ``_bytes`` not
  ``_byte``/``_kb``/``_mb``/``_gb``, ``_ratio`` not
  ``_pct``/``_percent``/``_frac``/``_fraction`` — the cost/HBM/SLO
  gauge families (``hbm_*_bytes``, ``*_coverage_ratio``,
  ``slo_*_burn_rate_ratio``) and the control-loop families (ISSUE 18:
  ``autoscale_*_total`` counters, ``autoscale_*_ratio`` /
  ``autoscale_target_replicas`` / ``serve_queue_depth_ewma`` gauges,
  the ``autoscale_decision_seconds`` histogram) depend on dashboards
  keying one spelling;
* **one family, one kind** across every module (the registry enforces
  it per instance at runtime; the lint catches cross-module collisions
  before they meet in one registry).

Dynamic names (f-strings) are invisible to the lint — keep them on the
same conventions by hand (the registry's kind guard still covers them
at runtime).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Tuple

from .framework import Finding, LintPass

METHODS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
HIST_UNIT_SUFFIXES = ("_seconds", "_ms", "_us", "_s", "_per_s",
                      "_bytes", "_ratio", "_pages")
# unitless histogram families that are ratios/fractions by nature
HIST_UNITLESS_OK = {"batch_occupancy"}
# canonical unit spellings (ISSUE 13): every kind — a counter named
# x_mb_total or a gauge named x_secs breaks the dashboards that key
# on one spelling per unit
BAD_UNIT_SUFFIXES = (
    ("_secs", "_seconds"), ("_sec", "_seconds"),
    ("_second", "_seconds"),
    ("_byte", "_bytes"), ("_kb", "_bytes"), ("_mb", "_bytes"),
    ("_gb", "_bytes"), ("_kib", "_bytes"), ("_mib", "_bytes"),
    ("_gib", "_bytes"),
    ("_pct", "_ratio"), ("_percent", "_ratio"), ("_frac", "_ratio"),
    ("_fraction", "_ratio"),
    # KV paging families (ISSUE 16): gen_kv_pages_* gauges and
    # gen_kv_page_*_total counters key dashboards on '_pages'/'_page_'
    ("_page", "_pages"), ("_pg", "_pages"),
    # embedding-tier families (ISSUE 19): embed_*_rows gauges and
    # embed_delta_rows_total count table ROWS — one spelling
    ("_row", "_rows"), ("_entry", "_rows"), ("_entries", "_rows"),
)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def target_files(root: str) -> Iterable[str]:
    pkg = os.path.join(root, "paddle1_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
    for fn in ("bench.py", "bench_utils.py"):
        p = os.path.join(root, fn)
        if os.path.exists(p):
            yield p


def collect(path: str):
    """Yield (kind, name, lineno) for every literal metric touch."""
    with open(path, "r") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return
    yield from collect_tree(tree)


def collect_tree(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in METHODS):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield fn.attr, arg.value, node.lineno


def _site_problems(kind: str, name: str) -> List[str]:
    """Rule messages for one (kind, name) touch — shared by the legacy
    string surface and the framework pass, so wording never drifts."""
    out = []
    if not NAME_RE.match(name):
        out.append(f"{kind} name {name!r} is not snake_case")
    if kind == "counter" and not name.endswith("_total"):
        out.append(f"counter {name!r} must end in '_total'")
    if kind in ("gauge", "histogram") and name.endswith("_total"):
        out.append(f"{kind} {name!r} must NOT end in "
                   "'_total' (that suffix promises a counter)")
    if kind == "histogram" \
            and not name.endswith(HIST_UNIT_SUFFIXES) \
            and name not in HIST_UNITLESS_OK:
        out.append(f"histogram {name!r} needs a unit suffix "
                   f"{HIST_UNIT_SUFFIXES} (or add it to the unitless "
                   "allowlist if it is a ratio)")
    base = name[:-len("_total")] if (kind == "counter"
                                     and name.endswith("_total")) \
        else name
    for bad, canon in BAD_UNIT_SUFFIXES:
        if base.endswith(bad):
            out.append(f"{kind} {name!r} uses non-canonical unit "
                       f"suffix {bad!r} — spell it {canon!r} (one "
                       "spelling per unit, the dashboard contract)")
            break
    return out


def check(files) -> list:
    """Legacy string-report surface (kept for the shim + tests)."""
    problems = []
    kinds_by_name: Dict[str, Dict[str, str]] = {}
    root = repo_root()
    for path in files:
        rel = os.path.relpath(path, root)
        for kind, name, lineno in collect(path):
            where = f"{rel}:{lineno}"
            for msg in _site_problems(kind, name):
                problems.append(f"{where}: {msg}")
            kinds_by_name.setdefault(name, {})[kind] = where
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            sites = ", ".join(f"{k} at {w}" for k, w in sorted(
                kinds.items()))
            problems.append(
                f"metric family {name!r} registered as multiple kinds: "
                f"{sites} — one family, one kind")
    return problems


class MetricNamesPass(LintPass):
    name = "metric-names"
    rules = ("metric-name",)
    roots = ("paddle1_tpu", "bench.py", "bench_utils.py")

    def begin(self) -> None:
        # name -> kind -> (path, line): cross-file kind-conflict state
        self._kinds: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def check_file(self, path, rel, src, tree):
        for kind, name, lineno in collect_tree(tree):
            for msg in _site_problems(kind, name):
                yield Finding(path, lineno, "metric-name", msg)
            self._kinds.setdefault(name, {})[kind] = (path, lineno)

    def finish(self):
        for name, kinds in sorted(self._kinds.items()):
            if len(kinds) > 1:
                sites = ", ".join(
                    f"{k} at {os.path.basename(p)}:{ln}"
                    for k, (p, ln) in sorted(kinds.items()))
                first = sorted(kinds.values())[0]
                yield Finding(
                    first[0], first[1], "metric-name",
                    f"metric family {name!r} registered as multiple "
                    f"kinds: {sites} — one family, one kind")


def main(argv=None) -> int:
    """Standalone entry (kept for the shim + existing tests)."""
    root = repo_root()
    problems = check(sorted(target_files(root)))
    for p in problems:
        print(p)
    if problems:
        print(f"\n{len(problems)} metric-name problem(s) "
              "(see tools/lint/metric_names.py header for the rules)")
        return 1
    print("metric names OK")
    return 0
