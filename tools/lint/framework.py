"""The shared lint framework (ISSUE 11 tentpole).

One AST walk, many passes: every checker in ``tools/lint`` is a
:class:`LintPass` — the framework owns the file walker, the single
parse per file, the ``# noqa: <rule> — reason`` suppression layer, and
the report format, so a new defect-class checker is ~a page of AST
logic, not another script with its own walker and CLI.

Suppression contract (the PR 2 bare-except convention, generalized):

* a finding on line L is suppressed iff line L carries
  ``# noqa: <rule> — reason`` naming the finding's rule — the reason is
  REQUIRED (the marker is documentation, not an escape hatch); a
  marker without one keeps the finding *and* adds a ``noqa-reason``
  finding;
* multiple rules may share one marker: ``# noqa: lock-blocking,
  guarded-mutation — reason``;
* passes that implement their own marker semantics (the bare-except
  pass, whose marker also changes *behavior* — a marked broad catch is
  allowed) set ``self_suppressing = True`` and the generic layer stays
  out of their way.

Run everything: ``python -m tools.lint --all`` (the CI entry).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# the runtime packages every pass defaults to (tests/ is deliberately
# absent: seeded violation fixtures live there)
DEFAULT_PATHS = ("paddle1_tpu", "tools", "bench.py", "benches.py",
                 "bench_utils.py")

# "# noqa: rule1,rule2 — reason" — the reason separator is an em/en
# dash or a spaced hyphen, so rule ids may themselves contain hyphens
_NOQA_RE = re.compile(r"#\s*noqa:\s*(.*)$")
_REASON_SPLIT_RE = re.compile(r"\s+[—–]\s*|\s+-\s+|\s*[—–]\s*")


class UnknownPassError(ValueError):
    """``--select`` named a pass that is not registered. Typed so
    programmatic callers can catch it; carries the registry so the CLI
    can teach instead of stack-trace."""

    def __init__(self, unknown, known_passes):
        self.unknown = sorted(unknown)
        self.known = list(known_passes)  # pass classes (name + rules)
        names = ", ".join(c.name for c in self.known)
        super().__init__(
            f"unknown pass(es) {self.unknown} — registered passes: "
            f"{names}")

    def teach(self) -> str:
        lines = [f"tools.lint: unknown pass(es) "
                 f"{', '.join(repr(u) for u in self.unknown)}",
                 "registered passes (use with --select):"]
        for c in self.known:
            lines.append(f"  {c.name:<18} rules: {', '.join(c.rules)}")
        lines.append("('python -m tools.lint --list' prints the same "
                     "registry)")
        return "\n".join(lines)


@dataclass
class Finding:
    """One lint hit: ``path:line: [rule] message``."""
    path: str
    line: int
    rule: str
    message: str

    def _rel(self, root: Optional[str]) -> str:
        p = self.path
        if root:
            try:
                rel = os.path.relpath(p, root)
                if not rel.startswith(".."):
                    p = rel
            except ValueError:  # pragma: no cover - windows drives
                pass
        return p

    def format(self, root: Optional[str] = None) -> str:
        return (f"{self._rel(root)}:{self.line}: [{self.rule}] "
                f"{self.message}")

    def as_dict(self, root: Optional[str] = None) -> Dict[str, object]:
        """The machine-readable shape of ``--format=json`` (exactly
        these four keys — the schema the round-trip test pins)."""
        return {"file": self._rel(root).replace(os.sep, "/"),
                "line": self.line, "rule": self.rule,
                "message": self.message}


@dataclass
class NoqaMarker:
    """A parsed ``# noqa: ...`` comment on one source line."""
    rules: Tuple[str, ...]
    reason: str
    line: int


def parse_noqa(line_text: str, lineno: int) -> Optional[NoqaMarker]:
    m = _NOQA_RE.search(line_text)
    if m is None:
        return None
    tail = m.group(1).strip()
    parts = _REASON_SPLIT_RE.split(tail, maxsplit=1)
    rules_part = parts[0].strip()
    reason = parts[1].strip() if len(parts) > 1 else ""
    rules = tuple(r.strip() for r in rules_part.split(",") if r.strip())
    return NoqaMarker(rules=rules, reason=reason, line=lineno)


class LintPass:
    """Base class for one defect-class checker.

    Subclasses set ``name`` (the ``--select`` id), ``rules`` (the ids a
    ``# noqa`` marker can name), and implement :meth:`check_file`;
    cross-file passes accumulate state there and emit from
    :meth:`finish`. ``roots`` limits which of the walked files the pass
    sees (repo-relative prefixes / filenames)."""

    name: str = ""
    rules: Tuple[str, ...] = ()
    roots: Tuple[str, ...] = DEFAULT_PATHS
    # True when the pass implements its own marker handling (the
    # bare-except pass): the generic suppression layer skips it
    self_suppressing: bool = False
    # True when the pass cross-references the WHOLE walk (flag-liveness
    # pairs defines against reads repo-wide): running it over a partial
    # file list (--changed) would fabricate findings, so the CLI skips
    # it there with a note
    whole_repo: bool = False

    def wants(self, rel_path: str) -> bool:
        rp = rel_path.replace(os.sep, "/")
        for root in self.roots:
            r = root.replace(os.sep, "/")
            if rp == r or rp.startswith(r + "/"):
                return True
        return False

    def begin(self) -> None:  # pragma: no cover - trivial default
        pass

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def run_passes(passes: Sequence[LintPass],
               paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None,
               respect_roots: bool = False) -> RunResult:
    """Walk once, parse once per file, fan out to every pass, apply the
    generic noqa layer, return sorted findings.

    Explicit ``paths`` normally see every selected pass (seeded test
    fixtures live outside the repo roots); ``respect_roots=True`` keeps
    the per-pass ``roots`` filter active anyway — the ``--changed``
    mode, whose file list is repo files that must lint exactly as the
    full ``--all`` walk would."""
    root = root or repo_root()
    explicit = paths is not None
    if paths is None:
        paths = [os.path.join(root, p) for p in DEFAULT_PATHS
                 if os.path.exists(os.path.join(root, p))]
    result = RunResult()
    lines_by_path: Dict[str, List[str]] = {}
    raw: List[Tuple[LintPass, Finding]] = []
    for p in passes:
        p.begin()
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            result.findings.append(Finding(path, 0, "io",
                                           f"unreadable ({e})"))
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        # explicit paths see every selected pass (seeded fixtures live
        # outside the repo roots); the default walk — and --changed,
        # which must match it — honors pass roots
        takers = (list(passes) if explicit and not respect_roots
                  else [p for p in passes if p.wants(rel)])
        if not takers:
            continue
        result.files_checked += 1
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            result.findings.append(Finding(
                path, e.lineno or 0, "syntax",
                f"syntax error: {e.msg}"))
            continue
        lines_by_path[path] = src.splitlines()
        for p in takers:
            for f in p.check_file(path, rel, src, tree):
                raw.append((p, f))
    for p in passes:
        for f in p.finish():
            raw.append((p, f))

    generic_rules = {r for p in passes if not p.self_suppressing
                     for r in p.rules}
    noreason_seen = set()
    for p, f in raw:
        if p.self_suppressing:
            result.findings.append(f)
            continue
        lines = lines_by_path.get(f.path, ())
        marker = None
        if 0 < f.line <= len(lines):
            marker = parse_noqa(lines[f.line - 1], f.line)
        if marker is not None and f.rule in marker.rules:
            if marker.reason:
                continue  # suppressed, documented
            key = (f.path, f.line)
            if key not in noreason_seen:
                noreason_seen.add(key)
                result.findings.append(Finding(
                    f.path, f.line, "noqa-reason",
                    "'# noqa: " + ",".join(marker.rules) + "' without "
                    "a reason — the marker documents WHY the "
                    "suppression is sound ('# noqa: <rule> — <reason>')"
                ))
            result.findings.append(f)
        else:
            result.findings.append(f)
    # a marker naming a generic rule on a line with NO finding but also
    # no reason is still an error: the allowlist must stay documentation
    for path, lines in lines_by_path.items():
        for i, text in enumerate(lines, start=1):
            if "``" in text:
                continue  # docstring prose QUOTING a marker, not one
            marker = parse_noqa(text, i)
            if marker is None or marker.reason:
                continue
            if (path, i) in noreason_seen:
                continue
            if any(r in generic_rules for r in marker.rules):
                noreason_seen.add((path, i))
                result.findings.append(Finding(
                    path, i, "noqa-reason",
                    "'# noqa: " + ",".join(marker.rules) + "' without "
                    "a reason — the marker documents WHY the "
                    "suppression is sound ('# noqa: <rule> — <reason>')"
                ))
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def report(result: RunResult, out=None, root: Optional[str] = None) -> int:
    out = out if out is not None else sys.stdout
    root = root or repo_root()
    for f in result.findings:
        print(f.format(root), file=out)
    if result.findings:
        print(f"tools.lint: {len(result.findings)} finding(s) across "
              f"{result.files_checked} file(s)", file=sys.stderr)
        return 1
    return 0


def findings_json(result: RunResult,
                  root: Optional[str] = None) -> str:
    """The ``--format=json`` document: a versioned object CI annotators
    parse (one entry per finding, file/line/rule/message)."""
    import json
    root = root or repo_root()
    return json.dumps(
        {"version": 1,
         "files_checked": result.files_checked,
         "findings": [f.as_dict(root) for f in result.findings]},
        indent=2)


def report_json(result: RunResult, out=None,
                root: Optional[str] = None) -> int:
    out = out if out is not None else sys.stdout
    print(findings_json(result, root), file=out)
    return 1 if result.findings else 0
