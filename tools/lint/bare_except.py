"""Lint pass: no exception handler may swallow interrupts.

Migrated from ``tools/check_no_bare_except.py`` (PR 2, extended PR 3/5)
into the unified framework — the standalone script is now a thin shim
over this module. The rules are unchanged; see :func:`check_source`.

The fault-tolerance stack is built on retry wrappers and
surface-worker-errors-later queues — exactly the code shapes that, when
written as ``except:`` or a swallowed ``except BaseException``, eat
``KeyboardInterrupt``/``SystemExit``/``SimulatedPreemption`` and turn
"ctrl-C the run" or "preempt the worker" into a silent hang. Enforced
over the runtime packages:

* **bare ``except:``** — always an error (it is ``except BaseException``
  in disguise);
* **``except BaseException`` / ``except KeyboardInterrupt`` /
  ``except SystemExit``** — an error unless the handler body contains a
  ``raise``, or the ``except`` line carries an explicit
  ``# noqa: broad-except`` marker documenting why the catch is sound;
* the marker itself must carry a **reason** (``# noqa: broad-except —
  why``) — a bare marker is an error: the allowlist is documentation,
  not an escape hatch;
* **``except SimulatedPreemption``** without re-raise — an error except
  in the designated preemption-handler files
  (``PREEMPTION_HANDLER_FILES``): a preemption notice must unwind to
  the resilient loop's handler (which checkpoints);
* **error-forwarding allowlist** (``ERROR_FORWARDING_FILES``): in the
  producer/worker loops of the input pipeline, ``except BaseException
  as e`` is sound *without* a marker when the handler demonstrably
  FORWARDS the caught object to its consumer — assigns it to an
  attribute (``self._err = e``) or ships it through a queue
  ``put``/``put_nowait`` — where it is re-raised on the consumer's next
  ``next()``/``read()``. Checked structurally, so the exemption cannot
  silently decay into a blanket pass.

Retry wrappers must catch ``Exception``, never broader.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Tuple

from .framework import Finding, LintPass, iter_py_files

MARKER = "noqa: broad-except"
DEFAULT_PATHS = ("paddle1_tpu", "tools", "bench.py", "benches.py")
BROAD_NAMES = {"BaseException", "KeyboardInterrupt", "SystemExit",
               "GeneratorExit"}
# catching the preemption notice without re-raising is only sound in
# the loop that OWNS preemption handling (checkpoint + resume); any
# other absorption — a supervisor retry wrapper, a cleanup path — turns
# "preempt the worker" into a silent hang or lost progress
PREEMPTION_NAMES = {"SimulatedPreemption"}
PREEMPTION_HANDLER_FILES = ("distributed/resilience.py",)
# files whose producer/worker loops may catch BaseException WITHOUT a
# marker IF the handler structurally forwards the exception object to
# its consumer (assignment or queue put — see module docstring)
ERROR_FORWARDING_FILES = ("io/dataloader.py", "fluid/reader.py")


def _forwards_exception(handler: ast.ExceptHandler) -> bool:
    """True iff the handler's body forwards the caught exception object
    to a CONSUMER-VISIBLE sink: the bound name (``except ... as e``) is
    assigned to an *attribute* (``self._err = e`` — re-raised on the
    consumer's next ``next()``) or appears in the arguments of a
    ``put``/``put_nowait`` call (shipped through a queue). A plain
    local binding (``msg = f"ignoring {e}"``) does NOT count — that is
    the decay-into-swallowing shape this check exists to reject; a
    handler that re-binds ``e`` to a wrapper and then sinks the new
    object still passes via the same two sinks."""
    name = handler.name
    if not name:
        return False

    def mentions(node: ast.AST) -> bool:
        return any(isinstance(sub, ast.Name) and sub.id == name
                   for sub in ast.walk(node))

    for sub in ast.walk(handler):
        if isinstance(sub, ast.Assign) and mentions(sub.value) and \
                any(isinstance(t, ast.Attribute) for t in sub.targets):
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("put", "put_nowait") and \
                    any(mentions(a) for a in sub.args):
                return True
    return False


def _exception_names(node: ast.expr) -> Iterator[str]:
    """Names caught by an except clause's type expression."""
    if isinstance(node, ast.Tuple):
        for elt in node.elts:
            yield from _exception_names(elt)
    elif isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr


def _contains_raise(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Raise):
            return True
    return False


def check_source(src: str, path: str = "<string>") -> List[Tuple[int, str]]:
    """(line, message) findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    return check_tree(tree, src, path)


def check_tree(tree: ast.AST, src: str,
               path: str = "<string>") -> List[Tuple[int, str]]:
    """The handler walk over an ALREADY-PARSED tree — the framework
    pass hands its per-file parse in here so the file is not parsed
    twice per lint run; :func:`check_source` wraps it for the legacy
    standalone surface."""
    findings: List[Tuple[int, str]] = []
    lines = src.splitlines()

    def marked(lineno: int) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return MARKER in line

    def marker_reason(lineno: int) -> str:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        _, _, tail = line.partition(MARKER)
        return tail.strip()

    norm_path = path.replace(os.sep, "/")
    preemption_handler = any(norm_path.endswith(suffix)
                             for suffix in PREEMPTION_HANDLER_FILES)
    error_forwarder = any(norm_path.endswith(suffix)
                          for suffix in ERROR_FORWARDING_FILES)

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        has_marker = marked(node.lineno)
        if has_marker and not marker_reason(node.lineno):
            findings.append((
                node.lineno,
                f"'# {MARKER}' without a reason — the marker documents "
                f"WHY the broad catch is sound ('# {MARKER} — <reason>')"))
        if node.type is None:
            if not has_marker:
                findings.append((
                    node.lineno,
                    "bare 'except:' swallows KeyboardInterrupt/"
                    "SystemExit — catch Exception (or narrower)"))
            continue
        broad = [n for n in _exception_names(node.type)
                 if n in BROAD_NAMES]
        if broad and error_forwarder and _forwards_exception(node):
            broad = []  # forwarded to the consumer, re-raised there
        if broad and not _contains_raise(node) and not has_marker:
            findings.append((
                node.lineno,
                f"'except {'/'.join(broad)}' without re-raise — a retry/"
                "cleanup wrapper here can swallow interrupts; catch "
                "Exception, re-raise, or justify with "
                f"'# {MARKER} — <reason>'"))
        preempt = [n for n in _exception_names(node.type)
                   if n in PREEMPTION_NAMES]
        if preempt and not _contains_raise(node) and not has_marker \
                and not preemption_handler:
            findings.append((
                node.lineno,
                f"'except {'/'.join(preempt)}' without re-raise outside "
                "the designated preemption handler "
                f"({', '.join(PREEMPTION_HANDLER_FILES)}) — a preemption "
                "notice must unwind to the resilient loop (which "
                "checkpoints), not die in a retry/cleanup wrapper"))
    return findings


class BareExceptPass(LintPass):
    """Framework adapter over :func:`check_source` (which owns its own
    marker semantics — a marked broad catch is *allowed*, not just
    suppressed — hence ``self_suppressing``)."""

    name = "bare-except"
    rules = ("broad-except",)
    roots = DEFAULT_PATHS
    self_suppressing = True

    def check_file(self, path, rel, src, tree):
        for lineno, msg in check_tree(tree, src, path):
            yield Finding(path, lineno, "broad-except", msg)


def main(argv=None) -> int:
    """Standalone entry (kept for the shim + existing tests)."""
    import sys
    argv = sys.argv[1:] if argv is None else argv
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = argv or [os.path.join(repo_root, p) for p in DEFAULT_PATHS]
    total = 0
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            print(f"{path}:0: unreadable ({e})")
            total += 1
            continue
        for lineno, msg in check_source(src, path):
            print(f"{path}:{lineno}: {msg}")
            total += 1
    if total:
        print(f"check_no_bare_except: {total} finding(s)",
              file=sys.stderr)
        return 1
    return 0
