"""Lint pass: lock discipline for the threaded runtime (ISSUE 11).

Three rules over every ``with <lock>:`` region (a context expression
whose final name component is ``lock``/``rlock``/``mutex``/``cond`` —
``self._lock``, ``client.cond``, ``_table_lock``, ``self._queue_cond``
all match):

* **lock-blocking** — a blocking call lexically inside a lock region:
  ``time.sleep``, a queue's ``get``/``put`` (receiver named like a
  queue: ``q``/``_q``/``*_q``/``*queue*``), socket ``sendall``/
  ``recv``/``accept``/``connect``, the serving wire helpers
  ``send_msg``/``recv_msg``, zero-positional-arg ``.join()`` (thread
  join; ``", ".join(xs)`` has an argument and is exempt), future
  ``.result()``, and ``subprocess.run``/``check_call``/
  ``check_output``/``communicate``. Holding a lock across any of these
  convoys every other thread that needs it against a sleep, a kernel
  buffer, or a wedged executable — the ``_on_transport_loss``
  sendall-under-lock class PR 7's review rounds hand-found.
  Intentional sites (e.g. a per-connection send lock whose entire job
  is serializing ``sendall``) carry ``# noqa: lock-blocking — reason``.
  ``cond.wait()`` is deliberately NOT in the list: a Condition wait
  releases its lock.

* **guarded-mutation** — the ``# guarded-by:`` convention. Declaring an
  attribute in ``__init__`` with a trailing comment::

      self._clients = {}   # guarded-by: self._lock

  makes every later mutation of ``self._clients`` (assignment,
  augmented assignment, subscript store/delete, or a mutator method
  call — ``append``/``pop``/``clear``/``update``/...) outside a ``with
  self._lock:`` region an error, in every method of that class
  (``__init__`` itself is exempt: construction happens-before
  publication). A ``threading.Condition(self._lock)`` attribute is
  recognized as an alias — holding ``self._queue_cond`` IS holding
  ``self._lock``. Several guards may be listed comma-separated; any
  one of them satisfies the check.

* **lock-order** — the per-class nested-``with`` acquisition graph:
  ``with a:`` containing ``with b:`` records the edge a→b, across all
  methods of the class (module-level regions graph per module). A
  cycle is a lock-order inversion — the deadlock the runtime sanitizer
  (``core/locks.py``) catches dynamically, reported here before the
  code ever runs.

The pass is lexical (no interprocedural analysis): a blocking call
hidden behind a helper function is the runtime sanitizer's job; this
pass keeps the obvious shapes out of review. Nested ``def``/``lambda``
bodies drop the held-lock stack — a closure defined under a lock does
not *execute* under it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import Finding, LintPass

# final identifier component that makes a `with` expression a lock
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|rlock|mutex|cond)$")
# receiver identifier segments that make .get/.put a QUEUE operation
_QUEUE_SEGMENTS = {"q", "queue", "queues"}

_BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "accept", "connect",
                   "result", "communicate", "send_msg", "recv_msg"}
_SUBPROCESS_FNS = {"run", "check_call", "check_output", "call"}
_MUTATOR_METHODS = {"append", "appendleft", "extend", "insert", "pop",
                    "popleft", "popitem", "remove", "discard", "clear",
                    "update", "setdefault", "add"}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([^#]+?)\s*$")


def _name_tail(node: ast.expr) -> Optional[str]:
    """Final identifier component of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs
        return "<?>"


def _is_lock_expr(node: ast.expr) -> bool:
    tail = _name_tail(node)
    return bool(tail and _LOCK_NAME_RE.search(tail))


def _is_queue_name(node: ast.expr) -> bool:
    tail = _name_tail(node)
    if not tail:
        return False
    return any(seg in _QUEUE_SEGMENTS
               for seg in tail.lower().split("_") if seg)


def _blocking_call(node: ast.Call) -> Optional[str]:
    """Why this call is blocking, or None."""
    fn = node.func
    if isinstance(fn, ast.Name):
        if fn.id == "sleep":
            return "sleep()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    if attr == "sleep":
        return f"{_expr_text(fn)}()"
    if attr == "join" and not node.args:
        return ".join() (thread/process join)"
    if attr in ("get", "put") and _is_queue_name(fn.value):
        return (f"queue .{attr}() (use the _nowait variant or move it "
                "outside the lock)")
    if attr in _BLOCKING_ATTRS:
        return f".{attr}()"
    if attr in _SUBPROCESS_FNS and _name_tail(fn.value) == "subprocess":
        return f"subprocess.{attr}()"
    return None


class _ClassInfo:
    """Per-class lock state: guard declarations, Condition aliases, and
    the acquisition-order graph."""

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        # attr -> (guard lock texts, declaration line)
        self.guards: Dict[str, Tuple[Tuple[str, ...], int]] = {}
        # "self._queue_cond" -> "self._lock" (Condition wraps it)
        self.aliases: Dict[str, str] = {}
        # lock text -> {inner lock text -> first edge line}
        self.order: Dict[str, Dict[str, int]] = {}

    def canon(self, lock_text: str) -> str:
        seen: Set[str] = set()
        while lock_text in self.aliases and lock_text not in seen:
            seen.add(lock_text)
            lock_text = self.aliases[lock_text]
        return lock_text


class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    rules = ("lock-blocking", "guarded-mutation", "lock-order")

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        lines = src.splitlines()
        findings: List[Finding] = []
        module_info = _ClassInfo("<module>", path)
        infos = [module_info]
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(node.name, path)
                infos.append(info)
                self._collect_guards(node, lines, info)
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._walk(meth, [], info, findings,
                                   in_init=(meth.name == "__init__"))
            else:
                self._walk(node, [], module_info, findings,
                           in_init=False)
        for info in infos:
            findings.extend(self._order_findings(info))
        return findings

    # -- guard declarations --------------------------------------------------

    def _collect_guards(self, cls: ast.ClassDef, lines: List[str],
                        info: _ClassInfo) -> None:
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            attr_targets = [
                t for t in targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"]
            if not attr_targets:
                continue
            value = node.value
            # alias: self.X = threading.Condition(self.Y)
            if value is not None and isinstance(value, ast.Call) \
                    and _name_tail(value.func) == "Condition" \
                    and value.args:
                inner = _expr_text(value.args[0])
                for t in attr_targets:
                    info.aliases[f"self.{t.attr}"] = inner
            line = (lines[node.lineno - 1]
                    if 0 < node.lineno <= len(lines) else "")
            m = _GUARDED_BY_RE.search(line)
            if m:
                # anything after an em/en dash is prose, not a guard
                spec = re.split(r"\s*[—–]", m.group(1), maxsplit=1)[0]
                guards = tuple(g.strip() for g in
                               spec.split(",") if g.strip())
                for t in attr_targets:
                    info.guards[t.attr] = (guards, node.lineno)

    # -- the walk ------------------------------------------------------------

    def _walk(self, node: ast.AST, held: List[Tuple[str, int]],
              info: _ClassInfo, findings: List[Finding],
              in_init: bool) -> None:
        """Recursive descent carrying the lexically-held lock stack.
        ``held`` entries are (canonical lock text, with-line)."""
        if isinstance(node, ast.With):
            for item in node.items:
                # the context expressions evaluate BEFORE acquisition
                self._walk(item.context_expr, held, info, findings,
                           in_init)
            pushed = 0
            for item in node.items:
                ctx = item.context_expr
                if _is_lock_expr(ctx):
                    lock = info.canon(_expr_text(ctx))
                    if held and held[-1][0] != lock:
                        info.order.setdefault(held[-1][0], {}) \
                            .setdefault(lock, node.lineno)
                    held.append((lock, node.lineno))
                    pushed += 1
            for child in node.body:
                self._walk(child, held, info, findings, in_init)
            for _ in range(pushed):
                held.pop()
            return

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def does not EXECUTE under the enclosing with
            for child in node.body:
                self._walk(child, [], info, findings, in_init)
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body, [], info, findings, in_init)
            return

        if isinstance(node, ast.Call) and held:
            why = _blocking_call(node)
            if why is not None:
                lock = held[-1][0]
                findings.append(Finding(
                    info.path, node.lineno, "lock-blocking",
                    f"blocking call {why} while holding {lock} "
                    f"(class {info.name}) — every thread needing the "
                    "lock convoys behind it; move the call outside "
                    "the region or justify with '# noqa: "
                    "lock-blocking — reason'"))

        if not in_init:
            self._check_mutation(node, held, info, findings)

        for child in ast.iter_child_nodes(node):
            self._walk(child, held, info, findings, in_init)

    def _check_mutation(self, node: ast.AST,
                        held: List[Tuple[str, int]], info: _ClassInfo,
                        findings: List[Finding]) -> None:
        """guarded-mutation: writes to declared attrs outside their
        lock."""
        if not info.guards:
            return
        mutated: List[Tuple[str, int]] = []

        def self_attr(expr: ast.expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                return expr.attr
            return None

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    a = self_attr(e)
                    if a is not None:
                        mutated.append((a, e.lineno))
                    elif isinstance(e, ast.Subscript):
                        a = self_attr(e.value)
                        if a is not None:
                            mutated.append((a, e.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    a = self_attr(t.value)
                    if a is not None:
                        mutated.append((a, t.lineno))
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in _MUTATOR_METHODS:
                a = self_attr(fn.value)
                if a is not None:
                    mutated.append((a, node.lineno))

        held_locks = {lock for lock, _ in held}
        for attr, lineno in mutated:
            decl = info.guards.get(attr)
            if decl is None:
                continue
            guards, decl_line = decl
            canon_guards = {info.canon(g) for g in guards}
            if held_locks & canon_guards:
                continue
            findings.append(Finding(
                info.path, lineno, "guarded-mutation",
                f"self.{attr} is declared '# guarded-by: "
                f"{', '.join(guards)}' (line {decl_line}) but is "
                "mutated here "
                + (f"under {sorted(held_locks)} "
                   if held_locks else "with no lock held ")
                + f"(class {info.name}) — wrap the mutation in the "
                  "declared lock or justify with '# noqa: "
                  "guarded-mutation — reason'"))

    # -- lock-order ----------------------------------------------------------

    def _order_findings(self, info: _ClassInfo) -> List[Finding]:
        """DFS the acquisition graph for cycles."""
        out: List[Finding] = []
        graph = info.order
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        reported: Set[Tuple[str, str]] = set()

        def dfs(n: str, stack: List[str]) -> None:
            color[n] = GRAY
            stack.append(n)
            for m, line in sorted(graph.get(n, {}).items()):
                if color.get(m, WHITE) == GRAY:
                    cyc = stack[stack.index(m):] + [m]
                    key = (min(cyc), max(cyc))
                    if key not in reported:
                        reported.add(key)
                        out.append(Finding(
                            info.path, line, "lock-order",
                            "lock-order inversion in "
                            f"{info.name}: acquisition cycle "
                            + " -> ".join(cyc)
                            + " — two threads taking these locks in "
                              "opposite orders deadlock; pick one "
                              "global order"))
                elif color.get(m, WHITE) == WHITE:
                    dfs(m, stack)
            stack.pop()
            color[n] = BLACK

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                dfs(n, [])
        return out
