"""Lint pass: every defined flag must have a reader (ISSUE 11).

The VERDICT dead-flag class: ``define_flag("x", ...)`` with validator
and help text but ZERO consumers — the flag validates, documents, and
does nothing. This pass cross-references every ``define_flag`` site
against every *read* across the walked files and fails on a flag
nobody reads.

What counts as a read — any of:

* the flag's name as a string literal anywhere inside the arguments of
  a call that is not ``define_flag`` itself: ``flag("x")``,
  ``flag_active("x")``, ``get_flags(["x"])``, ``set_flags({"x": v})``,
  ``_flag_default(arg, "x")``, ``resolve_buckets(...,
  spec_flag="x")`` all match (dict keys/values and nested literals
  included — the walk covers the whole argument subtree);
* the name as a function parameter's *default value*
  (``spec_flag: str = "serve_buckets"``);
* the textual environment form ``FLAGS_<name>`` anywhere in a walked
  file (the Supervisor/fleet env-propagation idiom).

Whole-string equality only: a flag named inside an error message or
help text ("raise serve_queue_depth") is a substring, not a read.

Flags kept for forward compatibility go in :data:`FORWARD_COMPAT`
with a reason naming the ROADMAP item that will read them — an entry
whose flag HAS readers (or no longer exists) is itself a finding, so
the allowlist cannot rot.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from .framework import Finding, LintPass

# flag name -> reason naming the ROADMAP item that will read it.
# (Empty today: the ISSUE 11 audit wired or deleted every dead flag —
# see MIGRATING.md "Flag registry discipline". Add entries here ONLY
# with a concrete ROADMAP pointer. A flag WIRED IN THE SAME PR that
# defines it must never need an entry: the pass cross-references reads
# across the whole walk, so define-in-flags.py + read-anywhere passes
# on its own — debug_jit_sanitizer (ISSUE 12) is the worked example,
# and tests/test_jit_lint.py pins the regression.)
FORWARD_COMPAT: Dict[str, str] = {}

_ENV_RE = re.compile(r"FLAGS_([A-Za-z_][A-Za-z0-9_]*)")


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


class FlagLivenessPass(LintPass):
    name = "flag-liveness"
    rules = ("dead-flag",)
    # define/read pairing only holds over the FULL walk: a partial file
    # list (--changed) would read every flag in a changed flags.py as
    # dead — the CLI skips this pass there
    whole_repo = True

    def begin(self) -> None:
        # name -> (path, line) of the define_flag site
        self.defined: Dict[str, Tuple[str, int]] = {}
        self.read: Set[str] = set()

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        for m in _ENV_RE.finditer(src):
            self.read.add(m.group(1))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if _call_name(node) == "define_flag":
                    if node.args and isinstance(node.args[0],
                                                ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        self.defined.setdefault(
                            node.args[0].value, (path, node.lineno))
                    continue  # help strings are not reads
                for arg in list(node.args) + [k.value for k
                                              in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            self.read.add(sub.value)
                        elif isinstance(sub, ast.Dict):
                            for k in sub.keys:
                                if isinstance(k, ast.Constant) \
                                        and isinstance(k.value, str):
                                    self.read.add(k.value)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for d in (list(node.args.defaults)
                          + [d for d in node.args.kw_defaults
                             if d is not None]):
                    if isinstance(d, ast.Constant) \
                            and isinstance(d.value, str):
                        self.read.add(d.value)
        return ()

    def finish(self) -> Iterable[Finding]:
        for name, (path, line) in sorted(self.defined.items()):
            if name in self.read:
                continue
            if name in FORWARD_COMPAT:
                if not FORWARD_COMPAT[name].strip():
                    yield Finding(
                        path, line, "dead-flag",
                        f"flag '{name}' is allowlisted forward-compat "
                        "with an EMPTY reason — name the ROADMAP item "
                        "that will read it")
                continue
            yield Finding(
                path, line, "dead-flag",
                f"flag '{name}' is defined but never read anywhere in "
                "the runtime packages (no flag()/get_flags()/"
                "set_flags() touch, no FLAGS_ env reference) — it "
                "validates and does nothing: wire it up, delete it, "
                "or allowlist it in tools/lint/flag_liveness.py "
                "FORWARD_COMPAT naming the ROADMAP item that will "
                "read it")
        for name, reason in sorted(FORWARD_COMPAT.items()):
            if name not in self.defined:
                # the define was deleted but the allowlist entry stayed
                yield Finding(
                    "tools/lint/flag_liveness.py", 0, "dead-flag",
                    f"FORWARD_COMPAT allowlists '{name}' but no "
                    "define_flag for it exists — remove the stale "
                    "entry")
            elif name in self.read:
                path, line = self.defined[name]
                yield Finding(
                    path, line, "dead-flag",
                    f"flag '{name}' is allowlisted forward-compat in "
                    "tools/lint/flag_liveness.py but HAS readers now "
                    "— remove the stale allowlist entry "
                    f"({reason!r})")
