"""Lint pass: rank-divergent collective schedules (ISSUE 14).

The hardest bug class left in this stack HANGS instead of erroring: a
collective reached by some ranks and not others. Every rank of an SPMD
job must issue the same collectives in the same order — a
``lax.psum`` (or a ``sync_global_devices`` barrier) inside a
``if rank == 0:`` branch means rank 0 waits at a rendezvous its peers
never reach, and the job wedges until a hang timeout fires with no
pointer at the cause. PR 2's multi-host commit originally shipped
exactly this shape (a rank-conditional retry skipped a barrier the
peers re-entered).

Three rules, all lexical (see ``tools/lint/collectivelib.py`` for what
counts as a collective and as a rank-conditional test):

* ``rank-divergent-collective`` — a collective call lexically inside a
  branch (``if``/``elif``/``else``/ternary/``while``) whose test is
  rank-conditional (``rank == 0``, ``process_index()``,
  ``PADDLE_TRAINER_ID``). Rank-uniform tests (``process_count() > 1``,
  a config flag) are fine — every rank takes the same arm.

* ``rank-divergent-skip`` — an early ``return``/``raise``/
  ``continue``/``break`` inside a rank-conditional branch when a
  collective appears LATER in the same function: the exiting rank
  skips a rendezvous its peers still enter.

* ``collective-swallow`` — a collective inside a ``try`` body whose
  handler does not re-raise: an exception on ONE rank (a full disk, a
  flaky socket) silently skips that rank's collective while the peers
  block in theirs. Handlers that re-raise (or raise anything) keep the
  ranks in lockstep — they all unwind.

Value-level rank selects (``jnp.where(axis_index(axis) == 0, ...)``)
are NOT control flow: every rank still executes the collective, so
``reduce``/``broadcast``-style masked implementations stay clean.
Intended divergence — a genuinely local rank-0-only fast path — takes
``# noqa: <rule> — reason``, making the exception greppable
documentation, like the host-sync budget. The runtime half of this
pass is ``core/collective_sanitizer.py``, which catches the schedules
a lexical view cannot link.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .collectivelib import (CollectiveCall, classify_collective,
                            collect_collectives, rank_condition_reason,
                            walk_skipping_nested_defs)
from .framework import Finding, LintPass

_EXIT_NODES = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _collectives_in(node: ast.AST) -> List[CollectiveCall]:
    """Collective calls in ``node``'s subtree, nested defs excluded."""
    out = []
    for sub in walk_skipping_nested_defs(node):
        if isinstance(sub, ast.Call):
            op = classify_collective(sub)
            if op is not None:
                out.append(CollectiveCall(
                    node=sub, lineno=sub.lineno, op=op, text=op))
    return out


class RankDivergencePass(LintPass):
    name = "rank-divergence"
    rules = ("rank-divergent-collective", "rank-divergent-skip",
             "collective-swallow")

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        findings: List[Finding] = []
        if not collect_collectives(tree):
            return findings  # no collectives anywhere: nothing to order
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            self._check_function(scope, path, findings)
        return findings

    # -- per-function -------------------------------------------------------

    def _check_function(self, fdef, path: str,
                        findings: List[Finding]) -> None:
        # every collective at THIS function's scope (closures excluded:
        # a traced inner `f(x)` has its own schedule obligations at its
        # own call sites)
        own = {c.node: c for c in _collectives_in(fdef)}
        if not own:
            return
        colls = sorted(own.values(), key=lambda c: c.lineno)
        flagged: set = set()  # nested rank-ifs must not double-report

        for node in walk_skipping_nested_defs(fdef):
            if isinstance(node, ast.If) or isinstance(node, ast.While):
                reason = rank_condition_reason(node.test)
                if reason is None:
                    continue
                self._check_rank_branch(node, reason, path, colls,
                                        flagged, findings)
            elif isinstance(node, ast.IfExp):
                reason = rank_condition_reason(node.test)
                if reason is None:
                    continue
                for arm in (node.body, node.orelse):
                    for c in _collectives_in(arm):
                        if c.node not in flagged:
                            flagged.add(c.node)
                            findings.append(self._divergent(
                                path, c, reason, node.lineno))
            elif isinstance(node, ast.Try):
                self._check_try(node, path, findings)

    def _check_rank_branch(self, branch, reason: str, path: str,
                           colls, flagged: set,
                           findings: List[Finding]) -> None:
        # arms of a rank-conditional execute on DISJOINT rank subsets:
        # a collective in either arm is reached by only some ranks
        arms = [branch.body]
        if branch.orelse:
            arms.append(branch.orelse)
        arm_colls = set()
        for arm in arms:
            for stmt in arm:
                for c in _collectives_in(stmt):
                    arm_colls.add(c.node)
                    if c.node not in flagged:
                        flagged.add(c.node)
                        findings.append(self._divergent(
                            path, c, reason, branch.lineno))
        # early exits inside the branch that skip a LATER collective in
        # the same function (lexically after the branch)
        for arm in arms:
            # continue/break whose enclosing loop sits INSIDE the arm
            # never leave the branch (the checkpoint retry-loop shape:
            # `for attempt: ... continue` under the process-0 guard
            # re-tries, it does not skip the broadcast after)
            inner_loop_stmts = set()
            for stmt in arm:
                for sub in walk_skipping_nested_defs(stmt):
                    if isinstance(sub, (ast.For, ast.While)):
                        for inner in walk_skipping_nested_defs(sub):
                            if inner is not sub:
                                inner_loop_stmts.add(inner)
            for stmt in arm:
                for sub in walk_skipping_nested_defs(stmt):
                    if not isinstance(sub, _EXIT_NODES):
                        continue
                    if isinstance(sub, (ast.Continue, ast.Break)) \
                            and (sub in inner_loop_stmts
                                 or isinstance(branch, ast.While)):
                        # when the rank-conditional IS a while loop,
                        # break/continue directly under it stay inside
                        # the loop protocol: break exits to the code
                        # after the loop (which every rank reaches),
                        # continue re-tests — neither skips a later
                        # collective
                        continue
                    later = next((c for c in colls
                                  if c.lineno > sub.lineno
                                  and c.node not in arm_colls), None)
                    if later is None:
                        continue
                    kind = type(sub).__name__.lower()
                    findings.append(Finding(
                        path, sub.lineno, "rank-divergent-skip",
                        f"{kind} under rank-conditional '{reason}' "
                        f"(line {branch.lineno}) skips the "
                        f"'{later.op}' collective at line "
                        f"{later.lineno} on this rank while peers "
                        "still enter it — the divergent schedule "
                        "deadlocks at the next rendezvous; hoist the "
                        "collective above the exit, or make every "
                        "rank take the exit together "
                        "('# noqa: rank-divergent-skip — reason' if "
                        "the later collective is truly unreachable "
                        "on the other arm)"))
                    break  # one finding per exit statement

    def _check_try(self, node: ast.Try, path: str,
                   findings: List[Finding]) -> None:
        swallower = self._swallowing_handler(node)
        if swallower is None:
            return
        # the else clause only runs when the body didn't raise, so a
        # one-rank exception skips its collectives exactly like the
        # body's; finally always runs and stays clean
        for stmt in list(node.body) + list(node.orelse):
            for c in _collectives_in(stmt):
                findings.append(Finding(
                    path, c.lineno, "collective-swallow",
                    f"'{c.op}' collective inside a try whose "
                    f"'except {swallower[1]}' handler (line "
                    f"{swallower[0]}) does not re-raise — an "
                    "exception on ONE rank silently skips this "
                    "rank's collective while peers block at the "
                    "rendezvous; re-raise past the collective, or "
                    "record the outcome and have EVERY rank act on "
                    "it together (the checkpoint commit-broadcast "
                    "pattern). '# noqa: collective-swallow — reason' "
                    "documents an intended best-effort site"))

    @staticmethod
    def _swallowing_handler(
            node: ast.Try) -> Optional[Tuple[int, str]]:
        """(line, caught-type text) of the first handler that can
        swallow — no ``raise`` anywhere in its body."""
        for h in node.handlers:
            reraises = any(isinstance(sub, ast.Raise)
                           for sub in ast.walk(h))
            if reraises:
                continue
            if h.type is None:
                caught = "<bare>"
            else:
                try:
                    caught = ast.unparse(h.type)
                except Exception:  # pragma: no cover
                    caught = "?"
            return (h.lineno, caught)
        return None

    @staticmethod
    def _divergent(path: str, c: CollectiveCall, reason: str,
                   guard_line: int) -> Finding:
        return Finding(
            path, c.lineno, "rank-divergent-collective",
            f"'{c.op}' collective inside a rank-conditional branch "
            f"('{reason}', line {guard_line}) — only some ranks reach "
            "it, so they block at a rendezvous their peers never "
            "enter (the hang-not-error class). Hoist the collective "
            "out of the branch and select the VALUE per rank instead "
            "(jnp.where(axis_index(..) == 0, ...)), or run it on "
            "every rank and mask. '# noqa: "
            "rank-divergent-collective — reason' documents a "
            "genuinely local fast path")
