"""Shared JIT-callable discovery for the JIT-discipline passes (ISSUE 12).

The donation-safety, retrace-hazard and hidden-host-sync passes all
need the same per-file facts: *which callables are jitted*, what their
donated/static argument positions are, and which names a call site can
use to reach them. This module computes that once per file.

What counts as a jit construction (lexical — the documented limit of
every pass built on this):

* ``jax.jit(fn, ...)`` / ``jit(fn, ...)`` call expressions, wherever
  they appear (an ``Assign`` records the target names as callable
  aliases: ``self._jit = jax.jit(step, donate_argnums=(0, 1))`` makes
  ``self._jit`` a donating callable at positions 0 and 1);
* ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` /
  ``@functools.partial(jax.jit, ...)`` decorators (the decorated name
  is the callable alias);
* ``donate_argnums``/``donate_argnames`` and ``static_argnums``/
  ``static_argnames`` keywords are read from literal ints/strings,
  tuples/lists of them, or either branch of a conditional expression
  (the engine's ``(0, 1) if donate else ()`` shape counts as donating
  at 0 and 1 — the pass checks the discipline of the donating
  configuration).

A jit object returned from a helper and called through a variable the
pass cannot link (``fn = self._table.get(bucket); fn(...)``) is
invisible here — that is the runtime sanitizer's job
(``core/jit_sanitizer.py``), not this one.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


def expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs
        return "<?>"


def _is_jit_func(node: ast.expr) -> bool:
    """True for ``jax.jit`` / ``jit`` (the callee of a jit wrap)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return False


def _literal_positions(node: Optional[ast.expr]) -> Tuple[int, ...]:
    """Int positions named by a donate_argnums/static_argnums literal:
    a constant int, a tuple/list of them, or the union of both branches
    of a conditional (``(0, 1) if donate else ()``)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    if isinstance(node, ast.IfExp):
        return tuple(sorted(set(_literal_positions(node.body))
                            | set(_literal_positions(node.orelse))))
    return ()


def _literal_names(node: Optional[ast.expr]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    if isinstance(node, ast.IfExp):
        return tuple(sorted(set(_literal_names(node.body))
                            | set(_literal_names(node.orelse))))
    return ()


@dataclass
class JitWrap:
    """One jit construction site."""
    lineno: int
    # alias texts a call site can use ("self._jit", "g", decorated name)
    names: Tuple[str, ...]
    wrapped: Optional[ast.FunctionDef]  # the traced body, when linkable
    donate_argnums: Tuple[int, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donating: bool = False


@dataclass
class JitInfo:
    wraps: List[JitWrap] = field(default_factory=list)
    # alias text -> wrap (last wins, matching runtime rebinding)
    by_name: Dict[str, JitWrap] = field(default_factory=dict)
    # FunctionDef nodes whose bodies run under trace
    traced_defs: Set[ast.FunctionDef] = field(default_factory=set)

    @property
    def any_donating(self) -> bool:
        return any(w.donating for w in self.wraps)


def _wrap_from_call(call: ast.Call,
                    defs: Dict[str, ast.FunctionDef]) -> Optional[JitWrap]:
    """A JitWrap for ``jax.jit(...)`` (or a partial of it), else None."""
    fn = call.func
    inner_args = call.args
    inner_kw = {k.arg: k.value for k in call.keywords if k.arg}
    if not _is_jit_func(fn):
        # partial(jax.jit, static_argnums=...) — the jit ref is arg 0
        if isinstance(fn, (ast.Name, ast.Attribute)) \
                and (getattr(fn, "id", None) == "partial"
                     or getattr(fn, "attr", None) == "partial") \
                and call.args and _is_jit_func(call.args[0]):
            inner_args = call.args[1:]
            inner_kw = {k.arg: k.value for k in call.keywords if k.arg}
        else:
            return None
    wrapped = None
    if inner_args:
        tgt = inner_args[0]
        tail = None
        if isinstance(tgt, ast.Name):
            tail = tgt.id
        elif isinstance(tgt, ast.Attribute):
            tail = tgt.attr  # self._decode_fn -> method _decode_fn
        if tail is not None:
            wrapped = defs.get(tail)
    donate = _literal_positions(inner_kw.get("donate_argnums"))
    donating = ("donate_argnums" in inner_kw
                or "donate_argnames" in inner_kw)
    return JitWrap(
        lineno=call.lineno, names=(), wrapped=wrapped,
        donate_argnums=donate,
        static_argnums=_literal_positions(inner_kw.get("static_argnums")),
        static_argnames=_literal_names(inner_kw.get("static_argnames")),
        donating=donating)


# one-entry memo: the framework parses each file once and runs every
# pass against the SAME tree object back to back, so caching the last
# (tree, info) pair collapses the three JIT passes' discovery walks
# into one per file (the PR 10 reparse lesson) while holding at most
# one extra tree alive
_last_info: Optional[Tuple[ast.AST, "JitInfo"]] = None


def collect_jit_info(tree: ast.AST) -> JitInfo:
    """One walk: every jit wrap, its alias names, and the set of
    function bodies that run under trace. Memoized per tree object."""
    global _last_info
    if _last_info is not None and _last_info[0] is tree:
        return _last_info[1]
    info = _collect_jit_info(tree)
    _last_info = (tree, info)
    return info


def _collect_jit_info(tree: ast.AST) -> JitInfo:
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    info = JitInfo()

    def register(wrap: JitWrap, names: Tuple[str, ...]) -> None:
        wrap.names = names
        info.wraps.append(wrap)
        for n in names:
            info.by_name[n] = wrap
        if wrap.wrapped is not None:
            info.traced_defs.add(wrap.wrapped)

    seen_calls: Set[ast.Call] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if isinstance(value, ast.Call):
                w = _wrap_from_call(value, defs)
                if w is not None:
                    seen_calls.add(value)
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    register(w, tuple(expr_text(t) for t in targets
                                      if isinstance(t, (ast.Name,
                                                        ast.Attribute))))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    w = _wrap_from_call(dec, defs)
                    if w is not None:
                        seen_calls.add(dec)
                        w.wrapped = node
                        register(w, (node.name,))
                elif _is_jit_func(dec):
                    w = JitWrap(lineno=node.lineno, names=(),
                                wrapped=node)
                    register(w, (node.name,))
    # bare jit calls not bound to a name (``return jax.jit(fn, ...)``):
    # still mark the wrapped body traced and the file donating
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node not in seen_calls:
            w = _wrap_from_call(node, defs)
            if w is not None:
                register(w, ())
    return info
