"""Lint pass: hidden host synchronization (ISSUE 12).

A device→host readback (``.item()``, ``float()``, ``np.asarray`` on a
device array) blocks the dispatching thread until the device drains —
the ~70 ms round trip the whole ``core/async_loss`` machinery exists
to keep off the step loop. Inside a *traced* body the same shapes are
worse: they either raise a ConcretizationTypeError or silently bake a
traced value into the executable. This pass flags both, in the two
region kinds where a sync is a defect rather than a choice:

* **traced bodies** — functions the file jits (see
  ``tools/lint/jitlib``): any ``float()``/``int()``/``bool()`` whose
  argument is not a pure shape expression (``int(np.shape(x)[0])`` is
  static under trace and fine), any ``.item()``/``.tolist()``/
  ``.numpy()``, and any ``np.asarray``/``np.array``.

* **``# hot-path`` regions** — a ``# hot-path[: name]`` comment on (or
  directly above) a ``def``/``for``/``while``/``with`` line marks that
  node's body as a latency-budgeted region (the engine step loop, the
  batcher dispatch, the decode loop). Inside one, ``.item()``/
  ``.tolist()``/``.numpy()`` on anything, ``float()`` of a bare
  name/attribute, and ``np.asarray``/``np.array`` of an *attribute*
  (device state lives on ``self``) are flagged. Intended syncs — the
  decode loop's one per-token readback — carry
  ``# noqa: hidden-host-sync — reason``, which is the point: the sync
  budget of a hot region becomes greppable documentation.

``jnp.asarray`` is deliberately NOT flagged: it is a host→device
transfer (or a no-op on device values), not a readback. The runtime
side of this pass is ``core.jit_sanitizer.note_host_sync``, which
counts real sync events inside ``hot_section`` regions when
``debug_jit_sanitizer`` is on.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from .framework import Finding, LintPass
from .jitlib import collect_jit_info

_HOT_RE = re.compile(r"#\s*hot-path\b")

_READBACK_METHODS = {"item", "tolist", "numpy"}
_SCALARIZERS = {"float", "int", "bool"}
_NP_MODULES = {"np", "numpy"}
_NP_SYNC_FNS = {"asarray", "array", "ascontiguousarray"}


def _np_call(node: ast.Call) -> bool:
    fn = node.func
    return (isinstance(fn, ast.Attribute)
            and fn.attr in _NP_SYNC_FNS
            and isinstance(fn.value, ast.Name)
            and fn.value.id in _NP_MODULES)


def _shape_like(node: ast.expr) -> bool:
    """Static-under-trace expressions: shapes, dims, lens, constants."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Subscript):
        return _shape_like(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "size")
    if isinstance(node, ast.BinOp):
        return _shape_like(node.left) and _shape_like(node.right)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "len":
            return True
        if isinstance(fn, ast.Attribute) and fn.attr in ("shape",
                                                         "ndim"):
            return True
    return False


class HostSyncPass(LintPass):
    name = "host-sync"
    rules = ("hidden-host-sync",)

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        findings: List[Finding] = []
        info = collect_jit_info(tree)
        hot_lines = {i for i, text in enumerate(src.splitlines(),
                                                start=1)
                     if _HOT_RE.search(text)}
        hot_nodes = []
        if hot_lines:
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.For,
                                     ast.While, ast.With)):
                    if node.lineno in hot_lines \
                            or node.lineno - 1 in hot_lines:
                        hot_nodes.append(node)
        for fdef in info.traced_defs:
            self._scan(fdef, path, findings, traced=True,
                       region=fdef.name)
        for node in hot_nodes:
            # a hot region nested in a traced body was already scanned
            # with the stricter rules
            if node not in info.traced_defs:
                self._scan(node, path, findings, traced=False,
                           region=getattr(node, "name", "hot region"))
        return findings

    def _scan(self, root: ast.AST, path: str,
              findings: List[Finding], traced: bool,
              region: str) -> None:
        where = (f"inside jit-traced '{region}'" if traced
                 else f"on the hot path ('{region}')")
        tail = (" — under trace this concretizes (error) or bakes a "
                "constant; move it outside the jitted body"
                if traced else
                " — a device→host readback stalls the dispatch "
                "pipeline here; move it off the hot path, batch it, "
                "or document the intended sync") \
            + " ('# noqa: hidden-host-sync — reason')"
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in _READBACK_METHODS and not node.args:
                findings.append(Finding(
                    path, node.lineno, "hidden-host-sync",
                    f".{fn.attr}() {where}{tail}"))
            elif isinstance(fn, ast.Name) and fn.id in _SCALARIZERS \
                    and len(node.args) == 1:
                arg = node.args[0]
                if traced:
                    if not _shape_like(arg):
                        findings.append(Finding(
                            path, node.lineno, "hidden-host-sync",
                            f"{fn.id}() on a traced value {where}"
                            f"{tail}"))
                elif fn.id == "float" and isinstance(
                        arg, (ast.Name, ast.Attribute)):
                    findings.append(Finding(
                        path, node.lineno, "hidden-host-sync",
                        f"float() {where}{tail}"))
            elif _np_call(node) and node.args:
                arg = node.args[0]
                if traced or isinstance(arg, ast.Attribute):
                    findings.append(Finding(
                        path, node.lineno, "hidden-host-sync",
                        f"np.{node.func.attr}"  # type: ignore[union-attr]
                        f"({'traced value' if traced else 'device state'})"
                        f" {where}{tail}"))
