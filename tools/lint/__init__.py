"""Unified static-analysis suite — ``python -m tools.lint`` (ISSUE 11).

One framework (:mod:`tools.lint.framework`), four passes:

* ``bare-except`` — no handler may swallow interrupts (PR 2, migrated);
* ``metric-names`` — the Prometheus naming contract (PR 9, migrated);
* ``lock-discipline`` — blocking calls under locks, ``# guarded-by:``
  mutation discipline, nested-``with`` lock-order cycles (new);
* ``flag-liveness`` — every ``define_flag`` needs a reader (new).

See README "Static analysis" for the conventions
(``# noqa: <rule> — reason``, ``# guarded-by: <lock>``) and
``core/locks.py`` for the runtime lock-order sanitizer that covers what
a lexical pass cannot.
"""

from __future__ import annotations

from .bare_except import BareExceptPass
from .flag_liveness import FlagLivenessPass
from .framework import (DEFAULT_PATHS, Finding, LintPass, RunResult,
                        iter_py_files, parse_noqa, repo_root, report,
                        run_passes)
from .lock_discipline import LockDisciplinePass
from .metric_names import MetricNamesPass

ALL_PASSES = (BareExceptPass, MetricNamesPass, LockDisciplinePass,
              FlagLivenessPass)

__all__ = ["ALL_PASSES", "BareExceptPass", "MetricNamesPass",
           "LockDisciplinePass", "FlagLivenessPass", "Finding",
           "LintPass", "RunResult", "run_passes", "report",
           "repo_root", "iter_py_files", "parse_noqa", "DEFAULT_PATHS",
           "make_passes", "run"]


def make_passes(select=None):
    """Instantiate the registered passes (all, or by ``name``)."""
    classes = ALL_PASSES
    if select:
        wanted = {s.strip() for s in select if s and s.strip()}
        classes = [c for c in ALL_PASSES if c.name in wanted]
        unknown = wanted - {c.name for c in classes}
        if unknown:
            raise SystemExit(
                f"unknown pass(es) {sorted(unknown)} — known: "
                f"{[c.name for c in ALL_PASSES]}")
    return [c() for c in classes]


def run(paths=None, select=None, root=None) -> RunResult:
    """Programmatic entry: run the (selected) passes, return findings."""
    return run_passes(make_passes(select), paths=paths, root=root)
