"""Unified static-analysis suite — ``python -m tools.lint`` (ISSUE 11,
extended with the JIT-discipline passes in ISSUE 12 and the
SPMD-discipline passes in ISSUE 14).

One framework (:mod:`tools.lint.framework`), nine passes:

* ``bare-except`` — no handler may swallow interrupts (PR 2, migrated);
* ``metric-names`` — the Prometheus naming contract (PR 9, migrated);
* ``lock-discipline`` — blocking calls under locks, ``# guarded-by:``
  mutation discipline, nested-``with`` lock-order cycles (ISSUE 11);
* ``flag-liveness`` — every ``define_flag`` needs a reader (ISSUE 11);
* ``donation-safety`` — use-after-donate and ``device_put`` aliasing
  at donating jit boundaries (ISSUE 12);
* ``retrace-hazard`` — constant-folded closures, non-hashable static
  args, host-scalar feedback loops (ISSUE 12);
* ``host-sync`` — hidden device→host readbacks in traced bodies and
  ``# hot-path`` regions (ISSUE 12);
* ``rank-divergence`` — collectives inside rank-conditional branches,
  early exits that skip a later collective, swallowed exceptions past
  one (the hang-not-error class, ISSUE 14);
* ``commit-protocol`` — the multi-host checkpoint commit discipline:
  process-0-guarded fs commits declared ``# commit-protocol:`` and
  paired with an outcome broadcast (ISSUE 14).

See README "Static analysis" for the conventions
(``# noqa: <rule> — reason``, ``# guarded-by: <lock>``,
``# hot-path``, ``# commit-protocol:``), ``core/locks.py`` for the
runtime lock-order sanitizer, ``core/jit_sanitizer.py`` for the
runtime half of the JIT-discipline suite, and
``core/collective_sanitizer.py`` for the runtime collective-schedule
sanitizer (per-rank journals + cross-rank verifier) — each covers what
a lexical pass cannot.
"""

from __future__ import annotations

from .bare_except import BareExceptPass
from .commit_protocol import CommitProtocolPass
from .donation_safety import DonationSafetyPass
from .flag_liveness import FlagLivenessPass
from .framework import (DEFAULT_PATHS, Finding, LintPass, RunResult,
                        UnknownPassError, findings_json, iter_py_files,
                        parse_noqa, repo_root, report, run_passes)
from .host_sync import HostSyncPass
from .lock_discipline import LockDisciplinePass
from .metric_names import MetricNamesPass
from .rank_divergence import RankDivergencePass
from .retrace_hazard import RetraceHazardPass

ALL_PASSES = (BareExceptPass, MetricNamesPass, LockDisciplinePass,
              FlagLivenessPass, DonationSafetyPass, RetraceHazardPass,
              HostSyncPass, RankDivergencePass, CommitProtocolPass)

__all__ = ["ALL_PASSES", "BareExceptPass", "MetricNamesPass",
           "LockDisciplinePass", "FlagLivenessPass",
           "DonationSafetyPass", "RetraceHazardPass", "HostSyncPass",
           "RankDivergencePass", "CommitProtocolPass",
           "Finding", "LintPass", "RunResult", "UnknownPassError",
           "run_passes", "report", "repo_root", "iter_py_files",
           "parse_noqa", "findings_json", "DEFAULT_PATHS",
           "make_passes", "run"]


def make_passes(select=None):
    """Instantiate the registered passes (all, or by ``name``).
    Raises :class:`UnknownPassError` (typed, carrying the registry)
    when a selected name is not registered."""
    classes = ALL_PASSES
    if select:
        wanted = {s.strip() for s in select if s and s.strip()}
        classes = [c for c in ALL_PASSES if c.name in wanted]
        unknown = wanted - {c.name for c in classes}
        if unknown:
            raise UnknownPassError(unknown, ALL_PASSES)
    return [c() for c in classes]


def run(paths=None, select=None, root=None) -> RunResult:
    """Programmatic entry: run the (selected) passes, return findings."""
    return run_passes(make_passes(select), paths=paths, root=root)
