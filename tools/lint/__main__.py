"""CLI for the unified lint suite: ``python -m tools.lint [--all]``.

Exit 0 clean, 1 with findings (one ``path:line: [rule] message`` per
finding), 2 on usage errors (an unknown ``--select`` name prints the
pass registry instead of a stack trace). ``--all`` (also the default
with no arguments) runs every registered pass over the runtime
packages; ``--select`` picks passes; positional paths narrow the walk;
``--budget-s`` fails the run when the wall time exceeds the budget
(the CI guard keeping lint growth out of the tier-1 cap).

``--changed`` (ISSUE 14) lints only the files that differ from the
git merge-base with ``--base`` (default ``main``) — committed,
staged, unstaged and untracked alike — for fast pre-commit runs;
``--all`` stays the CI path. Whole-repo passes (flag-liveness pairs
defines against reads across the full walk) are skipped there with a
note: a partial file list would fabricate findings.

``--format=json`` prints a versioned machine-readable document
(``{"version": 1, "files_checked": N, "findings": [{file, line,
rule, message}, ...]}``) so CI can annotate PRs; the schema is pinned
by a round-trip test.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional

from . import (ALL_PASSES, DEFAULT_PATHS, UnknownPassError, make_passes,
               repo_root, report, run_passes)
from .framework import report_json


def _git(root: str, *args: str) -> Optional[str]:
    try:
        p = subprocess.run(["git", "-C", root, *args],
                           capture_output=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if p.returncode != 0:
        return None
    return p.stdout.decode(errors="replace")


def collect_changed(root: str, base: str = "main") -> \
        Optional[List[str]]:
    """Absolute paths of ``.py`` files under the runtime roots that
    differ from the merge-base with ``base`` (falling back to ``HEAD``
    when the base ref does not exist — then only uncommitted work is
    linted), plus untracked files. None when ``root`` is not a git
    checkout."""
    mb = _git(root, "merge-base", "HEAD", base)
    if mb is None:
        # no such base ref (detached CI checkout, renamed default
        # branch): lint what is not yet committed rather than nothing
        mb = _git(root, "rev-parse", "HEAD")
    if mb is None:
        return None
    names = []
    diff = _git(root, "diff", "--name-only", mb.strip())
    if diff is not None:
        names += diff.splitlines()
    untracked = _git(root, "ls-files", "--others",
                     "--exclude-standard")
    if untracked is not None:
        names += untracked.splitlines()
    roots = tuple(r.rstrip("/") for r in DEFAULT_PATHS)
    out = []
    for name in sorted(set(n.strip() for n in names if n.strip())):
        if not name.endswith(".py"):
            continue
        if not any(name == r or name.startswith(r + "/")
                   for r in roots):
            continue
        path = os.path.join(root, name)
        if os.path.isfile(path):  # deleted files have no content
            out.append(path)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="unified static-analysis suite (see tools/lint/)")
    ap.add_argument("--all", action="store_true",
                    help="run every pass (the default when no --select "
                         "is given)")
    ap.add_argument("--select", default="",
                    help="comma-separated pass names, e.g. "
                         "--select lock-discipline,donation-safety")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files differing from the git "
                         "merge-base with --base (fast pre-commit "
                         "runs; whole-repo passes are skipped with a "
                         "note — --all stays the CI path)")
    ap.add_argument("--base", default="main",
                    help="merge-base ref for --changed "
                         "(default: main)")
    ap.add_argument("--format", default="text",
                    choices=("text", "json"),
                    help="findings output: human text (default) or "
                         "the versioned JSON document CI annotators "
                         "parse")
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="fail (exit 1) when the run takes longer than "
                         "this many seconds, findings or not — the CI "
                         "timing gate (0 disables)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to walk (default: the runtime "
                         "packages)")
    args = ap.parse_args(argv)
    if args.list:
        for c in ALL_PASSES:
            print(f"{c.name}: rules {', '.join(c.rules)}")
        return 0
    select = ([s for s in args.select.split(",") if s]
              if args.select and not args.all else None)
    try:
        passes = make_passes(select)
    except UnknownPassError as e:
        print(e.teach(), file=sys.stderr)
        return 2
    paths = args.paths or None
    run_root = None
    if args.changed:
        if args.paths:
            print("tools.lint: --changed and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        root = repo_root()
        changed = collect_changed(root, args.base)
        if changed is None:
            print(f"tools.lint: --changed needs a git checkout at "
                  f"{root} — falling back is unsafe, run --all",
                  file=sys.stderr)
            return 2
        skipped = [p.name for p in passes if p.whole_repo]
        if skipped:
            print("tools.lint: --changed skips whole-repo pass(es) "
                  f"{', '.join(skipped)} (define/read pairing needs "
                  "the full walk; --all covers them)",
                  file=sys.stderr)
            passes = [p for p in passes if not p.whole_repo]
        if not changed:
            print("tools.lint: nothing changed under the runtime "
                  "roots vs merge-base — clean", file=sys.stderr)
            return 0
        paths = changed
        run_root = root  # per-pass roots resolve against THIS checkout
    t0 = time.monotonic()
    # --changed file lists must lint exactly as --all would: keep the
    # per-pass roots filter active (metric-names deliberately skips
    # tools/, and a pre-commit red that CI-green --all suppresses
    # would teach people to ignore the tool)
    result = run_passes(passes, paths=paths, root=run_root,
                        respect_roots=args.changed)
    dt = time.monotonic() - t0
    if args.format == "json":
        rc = report_json(result)
    else:
        rc = report(result)
    if args.budget_s and dt > args.budget_s:
        print(f"tools.lint: run took {dt:.1f}s, over the "
              f"--budget-s {args.budget_s:g}s budget — a pass grew "
              "superlinear (or the walk picked up a new tree); "
              "profile it before it eats the tier-1 wall-time cap",
              file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
