"""CLI for the unified lint suite: ``python -m tools.lint [--all]``.

Exit 0 clean, 1 with findings (one ``path:line: [rule] message`` per
finding). ``--all`` (also the default with no arguments) runs every
registered pass over the runtime packages; ``--select`` picks passes;
positional paths narrow the walk.
"""

from __future__ import annotations

import argparse
import sys

from . import ALL_PASSES, make_passes, report, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="unified static-analysis suite (see tools/lint/)")
    ap.add_argument("--all", action="store_true",
                    help="run every pass (the default when no --select "
                         "is given)")
    ap.add_argument("--select", default="",
                    help="comma-separated pass names, e.g. "
                         "--select lock-discipline,flag-liveness")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to walk (default: the runtime "
                         "packages)")
    args = ap.parse_args(argv)
    if args.list:
        for c in ALL_PASSES:
            print(f"{c.name}: rules {', '.join(c.rules)}")
        return 0
    select = ([s for s in args.select.split(",") if s]
              if args.select and not args.all else None)
    passes = make_passes(select)
    result = run_passes(passes, paths=args.paths or None)
    return report(result)


if __name__ == "__main__":
    sys.exit(main())
