"""CLI for the unified lint suite: ``python -m tools.lint [--all]``.

Exit 0 clean, 1 with findings (one ``path:line: [rule] message`` per
finding), 2 on usage errors (an unknown ``--select`` name prints the
pass registry instead of a stack trace). ``--all`` (also the default
with no arguments) runs every registered pass over the runtime
packages; ``--select`` picks passes; positional paths narrow the walk;
``--budget-s`` fails the run when the wall time exceeds the budget
(the CI guard keeping lint growth out of the tier-1 cap).
"""

from __future__ import annotations

import argparse
import sys
import time

from . import ALL_PASSES, UnknownPassError, make_passes, report, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="unified static-analysis suite (see tools/lint/)")
    ap.add_argument("--all", action="store_true",
                    help="run every pass (the default when no --select "
                         "is given)")
    ap.add_argument("--select", default="",
                    help="comma-separated pass names, e.g. "
                         "--select lock-discipline,donation-safety")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="fail (exit 1) when the run takes longer than "
                         "this many seconds, findings or not — the CI "
                         "timing gate (0 disables)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to walk (default: the runtime "
                         "packages)")
    args = ap.parse_args(argv)
    if args.list:
        for c in ALL_PASSES:
            print(f"{c.name}: rules {', '.join(c.rules)}")
        return 0
    select = ([s for s in args.select.split(",") if s]
              if args.select and not args.all else None)
    try:
        passes = make_passes(select)
    except UnknownPassError as e:
        print(e.teach(), file=sys.stderr)
        return 2
    t0 = time.monotonic()
    result = run_passes(passes, paths=args.paths or None)
    dt = time.monotonic() - t0
    rc = report(result)
    if args.budget_s and dt > args.budget_s:
        print(f"tools.lint: run took {dt:.1f}s, over the "
              f"--budget-s {args.budget_s:g}s budget — a pass grew "
              "superlinear (or the walk picked up a new tree); "
              "profile it before it eats the tier-1 wall-time cap",
              file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
