"""Lint pass: buffer-donation discipline at the jit boundary (ISSUE 12).

Donation (``donate_argnums``) hands a buffer's storage to XLA: after
the dispatch, the Python object still exists but its device memory may
already hold the *output* — or be freed. On CPU (the tier-1 test
backend) donation silently degrades to a copy, so a use-after-donate
bug passes every test and corrupts training only on the TPU. That is
exactly how the PR 1 donation-aliasing bug deleted a live BertModel
embedding. Two rules make the shape a lint error:

* **use-after-donate** — inside one function, a variable passed at a
  donated position of a known donating jit callable (``self._jit =
  jax.jit(step, donate_argnums=(0, 1))`` … ``self._jit(self.params,
  …)``) is *read again* before being reassigned. The safe engine idiom
  — ``loss, self.params, … = self._jit(self.params, …)`` — reassigns
  the donated name in the same statement and is clean. The analysis is
  lexical and per-function: a donated buffer smuggled through a helper
  return is the runtime sanitizer's catch
  (``core.jit_sanitizer`` poisons donated buffers so *any* later use
  fails typed).

* **donated-alias** — in a file that builds a donating jit, a
  ``device_put`` whose source is a bare name/attribute (no intervening
  copy). ``device_put`` elides same-device copies per shard, so the
  result can alias the source buffer — donate the result and the
  source's storage is deleted out from under whoever still holds it
  (the PR 1 bug shape: single-device → replicated-on-mesh aliased the
  Layer's own array). The fix is ``device_put(jnp.array(v, copy=True),
  sharding)``; genuinely fresh sources (a buffer nothing else holds)
  carry ``# noqa: donated-alias — reason``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from .framework import Finding, LintPass
from .jitlib import JitInfo, collect_jit_info, expr_text


def _is_device_put(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "device_put"
    if isinstance(fn, ast.Name):
        return fn.id == "device_put"
    return False


def _is_bare_source(node: ast.expr) -> bool:
    """A device_put source that may alias live storage: a plain name or
    attribute chain (``v``, ``t.data``, ``self._buf``). A call
    (``jnp.array(v, copy=True)``, ``np.asarray(x)``) materializes a
    fresh buffer and is clean."""
    return isinstance(node, (ast.Name, ast.Attribute))


class DonationSafetyPass(LintPass):
    name = "donation-safety"
    rules = ("use-after-donate", "donated-alias")

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        info = collect_jit_info(tree)
        findings: List[Finding] = []
        if not info.any_donating:
            return findings
        # rule 2: aliasing device_put anywhere in a donating file
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_device_put(node) \
                    and node.args and _is_bare_source(node.args[0]):
                findings.append(Finding(
                    path, node.lineno, "donated-alias",
                    f"device_put({expr_text(node.args[0])}, ...) in a "
                    "file that donates buffers: device_put elides "
                    "same-device copies, so the result can ALIAS the "
                    "source — a later donating dispatch then deletes "
                    "the source's storage out from under its other "
                    "holders (the PR 1 embedding-deletion shape). Copy "
                    "first (device_put(jnp.array(v, copy=True), sh)) "
                    "or justify with '# noqa: donated-alias — reason'"))
        # rule 1: per-function use-after-donate
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(node, info, path, findings)
        return findings

    # -- use-after-donate ---------------------------------------------------

    def _check_function(self, fn: ast.AST, info: JitInfo, path: str,
                        findings: List[Finding]) -> None:
        # var text -> (donation line, callable text)
        donated: Dict[str, Tuple[int, str]] = {}

        def forget(target: ast.expr) -> None:
            elts = (target.elts if isinstance(target, ast.Tuple)
                    else [target])
            for e in elts:
                if isinstance(e, (ast.Name, ast.Attribute)):
                    donated.pop(expr_text(e), None)
                elif isinstance(e, ast.Starred):
                    forget(e.value)

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested scope: fresh analysis via the outer walk
            if isinstance(node, ast.Assign):
                visit(node.value)
                for t in node.targets:
                    forget(t)
                return
            if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if node.value is not None:
                    visit(node.value)
                if isinstance(node, ast.AugAssign):
                    # `x += ...` reads x first — flagged by the Load
                    # check below if donated, then counts as reassigned
                    check_load(node.target)
                forget(node.target)
                return
            if isinstance(node, ast.Call):
                for sub in list(node.args) + [k.value for k
                                              in node.keywords]:
                    visit(sub)
                visit(node.func)
                wrap = info.by_name.get(expr_text(node.func))
                if wrap is not None and wrap.donating:
                    for i in wrap.donate_argnums:
                        if i < len(node.args) and isinstance(
                                node.args[i], (ast.Name, ast.Attribute)):
                            donated[expr_text(node.args[i])] = (
                                node.lineno, expr_text(node.func))
                return
            if isinstance(node, (ast.Name, ast.Attribute)):
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    # a rebind (for-loop target, with-as, comprehension
                    # target) or del DISPOSES of the donated name — it
                    # is not a read of the donated storage
                    donated.pop(expr_text(node), None)
                else:
                    check_load(node)
                # fall through: an Attribute's .value may itself be a
                # donated name (self.params[...] reads self.params)
            for child in ast.iter_child_nodes(node):
                visit(child)

        def check_load(node: ast.expr) -> None:
            if not isinstance(node, (ast.Name, ast.Attribute)):
                return
            hit = donated.get(expr_text(node))
            if hit is not None:
                line, callee = hit
                findings.append(Finding(
                    path, node.lineno, "use-after-donate",
                    f"'{expr_text(node)}' was passed at a donated "
                    f"position of {callee} on line {line} — its device "
                    "storage now belongs to XLA (freed or holding the "
                    "output; on CPU the donation silently no-ops, so "
                    "tests won't catch it). Reassign it from the "
                    "dispatch result before reading, or justify with "
                    "'# noqa: use-after-donate — reason'"))

        body = getattr(fn, "body", [])
        for stmt in body:
            visit(stmt)
