"""Lint pass: the multi-host checkpoint commit protocol (ISSUE 14).

PR 2 hardened the multi-host checkpoint commit into a discipline:
every process feeds orbax the same path, but exactly ONE process
(process 0) stamps the manifest, renames the tmp dir into place and
runs GC — and then EVERY process learns the outcome through a
broadcast that doubles as the commit barrier, so peers raise together
on failure and a retry re-enters the collective save in lockstep.
PR 2's original bug was precisely the missing second half: a
rank-0-only commit retry without the outcome broadcast left peers
waiting at a barrier rank 0 never re-entered.

This pass makes the discipline declarable and checkable, the
``# guarded-by:`` way:

* ``commit-protocol`` — in a *multi-host-aware function* (one that
  consults ``process_index()``/``process_count()`` or the
  ``multihost_utils`` surface), a filesystem commit call
  (``os.replace``/``os.rename``/``shutil.rmtree``/``shutil.move``)
  must sit inside a process-0 guard (``if process_index() == 0:``),
  and that guard must DECLARE itself with a ``# commit-protocol:
  <name>`` marker comment on the guard line. An unguarded commit call
  is a finding at the call line (every process renames over the same
  path); an undeclared guard holding commit calls is a finding at the
  guard line (declare it so the pairing rule below can see it).

* ``commit-broadcast`` — every DECLARED commit-protocol guard must be
  paired, later in the same function, with an outcome
  broadcast/barrier (``broadcast_one_to_all``/``sync_global_devices``/
  ``barrier``): without it, peers either hang at the next rendezvous
  when process 0's commit failed and retried, or report success for a
  checkpoint that was never committed. The finding lands on the guard
  line — the PR 2 historical shape, caught lexically.

Helper functions that do fs renames but never consult the process
topology (``write_manifest``, a single-host ``_gc``) are out of
scope: the discipline binds where the code KNOWS it is multi-host.
Intended exceptions take ``# noqa: <rule> — reason``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .collectivelib import is_process0_guard, walk_skipping_nested_defs
from .framework import Finding, LintPass

_MARKER_RE = re.compile(r"#\s*commit-protocol:\s*(\S+)")

# (module, attr) pairs that commit filesystem state
_FS_COMMIT = {
    ("os", "replace"), ("os", "rename"), ("os", "renames"),
    ("shutil", "rmtree"), ("shutil", "move"),
}
_MULTIHOST_CALLS = frozenset({"process_index", "process_count"})
_OUTCOME_CALLS = frozenset({"broadcast_one_to_all",
                            "sync_global_devices", "barrier"})


def _call_mod_attr(node: ast.Call) -> Optional[Tuple[str, str]]:
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return (fn.value.id, fn.attr)
    return None


def _call_tail(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_fs_commit(node: ast.Call) -> bool:
    pair = _call_mod_attr(node)
    return pair is not None and pair in _FS_COMMIT


def _is_multihost_aware(fdef) -> bool:
    for node in walk_skipping_nested_defs(fdef):
        if isinstance(node, ast.Call) \
                and _call_tail(node) in _MULTIHOST_CALLS:
            return True
        if isinstance(node, ast.Attribute) \
                and node.attr == "multihost_utils":
            return True
        if isinstance(node, ast.Name) and node.id == "multihost_utils":
            return True
    return False


class CommitProtocolPass(LintPass):
    name = "commit-protocol"
    rules = ("commit-protocol", "commit-broadcast")

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        findings: List[Finding] = []
        lines = src.splitlines()
        markers: Dict[int, str] = {}
        for i, text in enumerate(lines, start=1):
            m = _MARKER_RE.search(text)
            if m:
                markers[i] = m.group(1)
        for fdef in [n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            if not _is_multihost_aware(fdef):
                continue
            self._check_function(fdef, path, markers, findings)
        return findings

    def _check_function(self, fdef, path: str, markers: Dict[int, str],
                        findings: List[Finding]) -> None:
        guards = [n for n in walk_skipping_nested_defs(fdef)
                  if isinstance(n, ast.If)
                  and is_process0_guard(n.test)]

        def guard_of(call: ast.Call) -> Optional[ast.If]:
            for g in guards:
                for sub in walk_skipping_nested_defs(g):
                    if sub is call:
                        return g
            return None

        # outcome broadcast/barrier call lines at function scope
        outcome_lines = [n.lineno for n in walk_skipping_nested_defs(fdef)
                         if isinstance(n, ast.Call)
                         and _call_tail(n) in _OUTCOME_CALLS]

        guards_with_commits = set()
        for node in walk_skipping_nested_defs(fdef):
            if not (isinstance(node, ast.Call) and _is_fs_commit(node)):
                continue
            g = guard_of(node)
            if g is None:
                pair = _call_mod_attr(node)
                findings.append(Finding(
                    path, node.lineno, "commit-protocol",
                    f"{pair[0]}.{pair[1]} in a multi-host-aware "
                    "function outside a process-0 guard — EVERY "
                    "process commits/renames/sweeps the same path "
                    "(racing renames, N-fold GC). Guard it with "
                    "'if process_index() == 0:' declared as "
                    "'# commit-protocol: <name>', or "
                    "'# noqa: commit-protocol — reason' for a "
                    "genuinely per-process path"))
            else:
                guards_with_commits.add(g)

        for g in guards_with_commits:
            declared = markers.get(g.lineno)
            if declared is None:
                findings.append(Finding(
                    path, g.lineno, "commit-protocol",
                    "process-0 guard performs filesystem commits but "
                    "declares no protocol — add '# commit-protocol: "
                    "<name>' on the guard line so the outcome-"
                    "broadcast pairing is checkable (the PR 2 "
                    "discipline: one committer, everyone learns the "
                    "outcome)"))
                continue
            guard_end = getattr(g, "end_lineno", g.lineno) or g.lineno
            if not any(ln > guard_end for ln in outcome_lines):
                findings.append(Finding(
                    path, g.lineno, "commit-broadcast",
                    f"commit-protocol '{declared}' guard has no "
                    "outcome broadcast/barrier after it in this "
                    "function — peers never learn whether process "
                    "0's commit succeeded: on failure they hang at "
                    "the next rendezvous (the PR 2 retry-mismatch "
                    "hang) or report success for an uncommitted "
                    "checkpoint. Follow the guard with "
                    "broadcast_one_to_all(ok)/sync_global_devices "
                    "so every process raises (and retries) "
                    "together"))
