"""Lint pass: retrace hazards at the jit boundary (ISSUE 12).

Every distinct signature a jitted callable sees is a full XLA compile;
the engines bucket shapes and warn once (``jit_retrace_warn``) exactly
because a silent retrace storm re-serializes the host loop behind the
compiler. Three lexical shapes cause storms (or their quieter cousin,
silent constant-folding) and are flaggable before the code runs:

* **retrace-closure** — a jitted function reads a module-level array
  (``TABLE = np.arange(...)`` … used inside an ``@jax.jit`` body). The
  closure capture is traced as a *constant*: the array is baked into
  the executable (bloating it, re-baking on every retrace) and any
  later rebinding of the module global is silently invisible to the
  compiled code. Thread it through the signature instead.

* **retrace-static-arg** — a call site of a ``static_argnums``/
  ``static_argnames`` callable passes a non-hashable literal (list /
  dict / set display, or an ``np.array(...)``-family call) at a static
  position: ``TypeError: unhashable`` at best, a per-call retrace at
  worst (every new value of a static arg is a new executable). Pass a
  tuple, or make the argument traced.

* **retrace-scalar-feedback** — inside a loop, a value produced by a
  jitted call is pulled to host (``float()`` / ``int()`` / ``bool()``
  / ``.item()``) and a name derived from it is fed back into a jitted
  call: the readback serializes every iteration behind the device (the
  async_loss machinery exists to avoid exactly this), and if the
  scalar rides a static or shape position each new value is a fresh
  compile. Keep the feedback on device (``lax.scan`` / carry) or batch
  the readbacks.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import Finding, LintPass
from .jitlib import collect_jit_info, expr_text

# module-level creators whose results are array-valued (the
# constant-folding closure hazard); receiver must be np/numpy/jnp
_ARRAY_FNS = {"array", "asarray", "zeros", "ones", "full", "empty",
              "arange", "linspace", "eye", "load", "loadtxt",
              "rand", "randn", "normal", "uniform"}
_ARRAY_MODULES = {"np", "numpy", "jnp"}

_SCALARIZERS = {"float", "int", "bool"}


def _array_creator(node: ast.expr) -> bool:
    """``np.arange(...)`` / ``jnp.zeros(...)``-family call."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _ARRAY_FNS:
        return False
    root = fn.value
    while isinstance(root, ast.Attribute):  # np.random.rand
        root = root.value
    return isinstance(root, ast.Name) and root.id in _ARRAY_MODULES


def _unhashable_literal(node: ast.expr) -> Optional[str]:
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if _array_creator(node):
        return "array"
    return None


class RetraceHazardPass(LintPass):
    name = "retrace-hazard"
    rules = ("retrace-closure", "retrace-static-arg",
             "retrace-scalar-feedback")

    def check_file(self, path: str, rel: str, src: str,
                   tree: ast.AST) -> Iterable[Finding]:
        info = collect_jit_info(tree)
        findings: List[Finding] = []
        if not info.wraps:
            return findings

        # -- retrace-closure: module-level arrays read in traced bodies
        module_arrays: Dict[str, int] = {}
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, ast.Assign) and _array_creator(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_arrays[t.id] = node.lineno
        for fdef in info.traced_defs:
            if not module_arrays:
                break
            local: Set[str] = {a.arg for a in fdef.args.args
                               + fdef.args.kwonlyargs
                               + fdef.args.posonlyargs}
            if fdef.args.vararg:
                local.add(fdef.args.vararg.arg)
            if fdef.args.kwarg:
                local.add(fdef.args.kwarg.arg)
            for node in ast.walk(fdef):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Store):
                    local.add(node.id)
            for node in ast.walk(fdef):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in module_arrays \
                        and node.id not in local:
                    findings.append(Finding(
                        path, node.lineno, "retrace-closure",
                        f"jitted '{fdef.name}' closes over module-"
                        f"level array '{node.id}' (defined line "
                        f"{module_arrays[node.id]}) — the capture is "
                        "baked into the executable as a constant "
                        "(re-baked per retrace; rebinding the global "
                        "is silently ignored). Pass it through the "
                        "function's signature, or justify with "
                        "'# noqa: retrace-closure — reason'"))

        # -- retrace-static-arg: non-hashable values at static positions
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            wrap = info.by_name.get(expr_text(node.func))
            if wrap is None or not (wrap.static_argnums
                                    or wrap.static_argnames):
                continue
            for i in wrap.static_argnums:
                if i < len(node.args):
                    kind = _unhashable_literal(node.args[i])
                    if kind:
                        findings.append(self._static_finding(
                            path, node.args[i].lineno, i, kind,
                            expr_text(node.func)))
            for kw in node.keywords:
                if kw.arg in wrap.static_argnames:
                    kind = _unhashable_literal(kw.value)
                    if kind:
                        findings.append(self._static_finding(
                            path, kw.value.lineno, kw.arg, kind,
                            expr_text(node.func)))

        # -- retrace-scalar-feedback inside loops
        jit_names = set(info.by_name)
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                self._check_loop(node, jit_names, info, path, findings)
        return findings

    @staticmethod
    def _static_finding(path: str, line: int, pos, kind: str,
                        callee: str) -> Finding:
        return Finding(
            path, line, "retrace-static-arg",
            f"{callee} takes static argument {pos!r}, but this call "
            f"site passes a {kind} there — non-hashable (TypeError at "
            "dispatch) and, were it hashable, every distinct value "
            "would be a fresh XLA compile. Pass a tuple / hashable "
            "constant, or make the argument traced; or justify with "
            "'# noqa: retrace-static-arg — reason'")

    def _check_loop(self, loop: ast.AST, jit_names: Set[str], info,
                    path: str, findings: List[Finding]) -> None:
        """float(jitted result) fed back into a jitted signature
        within the same loop body."""

        def is_jit_call(node: ast.expr) -> bool:
            return (isinstance(node, ast.Call)
                    and expr_text(node.func) in jit_names)

        jit_results: Set[str] = set()
        scalarized: Set[str] = set()

        def scalarizes(value: ast.expr) -> bool:
            # float(X)/int(X)/bool(X) or X.item() where X is a jitted
            # call or a name assigned from one in this loop
            if isinstance(value, ast.Call):
                fn = value.func
                if isinstance(fn, ast.Name) and fn.id in _SCALARIZERS \
                        and value.args:
                    inner = value.args[0]
                    return is_jit_call(inner) or (
                        isinstance(inner, (ast.Name, ast.Attribute))
                        and expr_text(inner) in jit_results)
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in ("item", "tolist"):
                    return (is_jit_call(fn.value) or
                            expr_text(fn.value) in jit_results)
            return False

        # pass 1: collect assignments in loop-body source order
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                names = [expr_text(t) for t in node.targets
                         if isinstance(t, (ast.Name, ast.Attribute))]
                if is_jit_call(node.value):
                    jit_results.update(names)
                elif scalarizes(node.value):
                    scalarized.update(names)
        if not scalarized:
            return
        # pass 2: a scalarized name feeding a jitted call
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) \
                    and expr_text(node.func) in jit_names:
                feeds = [expr_text(a) for a in node.args
                         if isinstance(a, (ast.Name, ast.Attribute))
                         and expr_text(a) in scalarized]
                for name in feeds:
                    findings.append(Finding(
                        path, node.lineno, "retrace-scalar-feedback",
                        f"'{name}' is a host scalar pulled out of a "
                        "jitted result in this loop and fed back into "
                        f"{expr_text(node.func)} — the readback "
                        "serializes every iteration behind the device "
                        "(and a static/shape position would recompile "
                        "per value). Carry the value on device "
                        "(lax.scan / fori_loop) or batch the "
                        "readbacks; or justify with '# noqa: "
                        "retrace-scalar-feedback — reason'"))
