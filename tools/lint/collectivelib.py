"""Shared collective-call discovery for the SPMD-discipline passes
(ISSUE 14) — the ``jitlib`` sibling.

The rank-divergence and commit-protocol passes both need the same
per-file facts: *which call sites are collectives* (operations every
rank of an SPMD program must reach in the same order) and *which
conditionals partition the ranks* (branches whose arms execute on
disjoint rank subsets). This module computes both, memoized per tree
the way ``jitlib.collect_jit_info`` is.

What counts as a collective (lexical — the documented limit of every
pass built on this):

* ``lax``-level named-axis collectives: ``psum``/``pmean``/``pmax``/
  ``pmin``/``psum_scatter``/``all_gather``/``ppermute``/``pshuffle``/
  ``all_to_all`` — matched as bare names or behind a ``lax``/
  ``jax.lax`` attribute (NOT plain ``lax.reduce``/``lax.broadcast``,
  which are local shape/monoid ops);
* multi-host coordination: ``sync_global_devices`` /
  ``broadcast_one_to_all`` / ``process_allgather`` (the
  ``multihost_utils`` surface);
* the repo's eager wrappers (``distributed/collective.py``):
  ``all_reduce``/``all_gather``/``reduce_scatter``/``alltoall``/
  ``barrier``/``hierarchical_all_reduce`` as bare names, plus
  ``reduce``/``broadcast``/``scatter``/``send``/``recv`` when reached
  through a ``dist``/``distributed``/``collective`` attribute (bare
  ``reduce`` would catch ``functools.reduce``).

What counts as a *rank-conditional* test — an expression that can
evaluate differently on different ranks of the same job:

* a call whose callee's final name is ``process_index``/``get_rank``/
  ``axis_index``/``local_rank``/``node_rank``;
* a name (or attribute's final component) that IS or ends in ``rank``,
  or is ``trainer_id``/``rank_id``/``proc_id``/``process_id``;
* the env spellings: a string literal ``PADDLE_TRAINER_ID`` or
  ``RANK`` anywhere inside the test.

``process_count()``/``get_world_size()`` are deliberately NOT
rank-conditional: the world size is uniform across ranks, and
``if process_count() > 1:`` is the standard single-host fast path.

A collective reached through a helper the pass cannot link
(``fn = table[op]; fn(x)``) is invisible here — that is the runtime
sanitizer's job (``core/collective_sanitizer.py``), not this one.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import List, Optional, Tuple

# distinctive collective names: safe to match as BARE calls too
BARE_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "ppermute", "pshuffle", "all_to_all", "alltoall", "all_reduce",
    "reduce_scatter", "barrier", "hierarchical_all_reduce",
    "sync_global_devices", "broadcast_one_to_all", "process_allgather",
})
# generic names that are collectives only behind a collective-module
# attribute (bare `reduce` is functools.reduce, `broadcast` is
# numpy/lax shape broadcasting)
QUALIFIED_COLLECTIVES = frozenset({
    "reduce", "broadcast", "scatter", "send", "recv",
})
# module aliases whose attributes make QUALIFIED_COLLECTIVES real
# collectives (the repo's import spellings)
_COLLECTIVE_MODULES = frozenset({
    "dist", "distributed", "collective", "paddle_dist", "cc",
})
# lax-level names valid ONLY behind lax/jax.lax (none currently beyond
# BARE — kept separate so lax.broadcast never matches)
_LAX_MODULES = frozenset({"lax"})
_MULTIHOST_MODULES = frozenset({"multihost_utils"})

_RANK_CALLS = frozenset({
    "process_index", "get_rank", "axis_index", "local_rank",
    "node_rank", "get_local_rank",
})
_RANK_NAMES = frozenset({
    "rank", "trainer_id", "rank_id", "proc_id", "process_id", "grank",
    "my_rank", "local_rank", "worker_rank",
})
_RANK_ENV_STRINGS = frozenset({"PADDLE_TRAINER_ID", "RANK"})


@dataclass
class CollectiveCall:
    """One lexical collective call site."""
    node: ast.Call
    lineno: int
    op: str          # canonical op name ("psum", "barrier", ...)
    text: str        # how the source spells it ("lax.psum", "barrier")


def _tail(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _base_name(node: ast.expr) -> Optional[str]:
    """Final name component of an attribute's VALUE: ``jax.lax.psum``
    -> ``lax``, ``dist.all_reduce`` -> ``dist``."""
    if isinstance(node, ast.Attribute):
        v = node.value
        if isinstance(v, ast.Attribute):
            return v.attr
        if isinstance(v, ast.Name):
            return v.id
    return None


def classify_collective(call: ast.Call) -> Optional[str]:
    """Canonical op name when ``call`` is a collective, else None."""
    fn = call.func
    name = _tail(fn)
    if name is None:
        return None
    if isinstance(fn, ast.Name):
        return name if name in BARE_COLLECTIVES else None
    base = _base_name(fn)
    if name in BARE_COLLECTIVES:
        # attribute spellings of the distinctive names are collectives
        # from any plausible module (lax.psum, dist.all_gather,
        # multihost_utils.sync_global_devices) — EXCEPT obvious
        # non-modules like a method on a list (`x.all_gather` would be
        # exotic enough to flag anyway)
        return name
    if name in QUALIFIED_COLLECTIVES and base is not None \
            and base.lower() in _COLLECTIVE_MODULES:
        return name
    return None


def _expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs
        return "<?>"


def collect_collectives(root: ast.AST) -> List[CollectiveCall]:
    """Every lexical collective call under ``root`` (document order)."""
    out: List[CollectiveCall] = []
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            op = classify_collective(node)
            if op is not None:
                out.append(CollectiveCall(
                    node=node, lineno=node.lineno, op=op,
                    text=_expr_text(node.func)))
    out.sort(key=lambda c: c.lineno)
    return out


def rank_condition_reason(test: ast.expr) -> Optional[str]:
    """Why ``test`` is rank-conditional (a short source fragment for
    the finding message), or None when it is rank-uniform."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            callee = _tail(node.func)
            if callee in _RANK_CALLS:
                return _expr_text(node.func) + "()"
        elif isinstance(node, ast.Name):
            nid = node.id.lower()
            if nid in _RANK_NAMES or nid.endswith("_rank"):
                return node.id
        elif isinstance(node, ast.Attribute):
            attr = node.attr.lower()
            if attr in _RANK_NAMES or attr.endswith("_rank"):
                return _expr_text(node)
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str) \
                and node.value in _RANK_ENV_STRINGS:
            return f"env {node.value!r}"
    return None


def is_process0_guard(test: ast.expr) -> bool:
    """True for the declared-commit-guard shape: a comparison of a
    rank expression against the literal 0 (``process_index() == 0``,
    ``rank == 0``, ``self.rank == 0``), or ``not process_index()``."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = test.operand
        return (isinstance(inner, ast.Call)
                and _tail(inner.func) in _RANK_CALLS)
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return False
    if not isinstance(test.ops[0], ast.Eq):
        return False
    sides = (test.left, test.comparators[0])
    zero = any(isinstance(s, ast.Constant) and s.value == 0
               and not isinstance(s.value, bool) for s in sides)
    ranky = any(rank_condition_reason(s) is not None for s in sides)
    return zero and ranky


def function_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    """Every function/method def in the module (outermost first)."""
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def walk_skipping_nested_defs(root: ast.AST):
    """``ast.walk`` over ``root``'s subtree that does not descend into
    nested function/class bodies — a closure defined inside a branch
    does not EXECUTE inside it (the lock-discipline lesson)."""
    yield root
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE_NODES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
