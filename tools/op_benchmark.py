#!/usr/bin/env python
"""Config-driven operator micro-benchmark harness.

Analog of the reference's
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc +
op_tester_config.cc: a config file describes {op, input shapes, dtype,
repeat}; the harness builds random inputs, runs the op, and reports
timing. TPU-native: each case is timed eagerly AND under jit (compiled,
block_until_ready per repeat), since the jit number is the one that
matters on TPU.

Usage:
    python tools/op_benchmark.py --config tools/op_bench_example.json
    python tools/op_benchmark.py --op matmul --shapes 512x512,512x512 \
        --dtype float32 --repeat 20

Config JSON: a list of cases:
    [{"op": "nn.functional.relu", "shapes": ["1024x1024"],
      "dtype": "float32", "repeat": 50, "backward": true}]

Op names resolve inside the paddle1_tpu namespace (e.g. "add",
"ops.math_ops.matmul", "nn.functional.softmax").
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _resolve(op_name: str):
    import paddle1_tpu as paddle
    obj = paddle
    for part in op_name.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            break
    if obj is None or not callable(obj):
        # common fallbacks: paddle.<name>, nn.functional.<name>,
        # ops.math_ops.<name>
        for prefix in ("", "nn.functional.", "ops.math_ops.",
                       "ops.manip_ops.", "ops.linalg_ops."):
            obj = paddle
            ok = True
            for part in (prefix + op_name).split("."):
                if not part:
                    continue
                obj = getattr(obj, part, None)
                if obj is None:
                    ok = False
                    break
            if ok and callable(obj):
                return obj
        raise SystemExit(f"cannot resolve op {op_name!r}")
    return obj


def _parse_shape(s: str):
    return tuple(int(d) for d in s.lower().split("x"))


def run_case(case: dict) -> dict:
    import jax
    import jax.numpy as jnp
    from paddle1_tpu.core.tensor import to_tensor

    op = _resolve(case["op"])
    shapes = [_parse_shape(s) for s in case["shapes"]]
    dtype = case.get("dtype", "float32")
    repeat = int(case.get("repeat", 10))
    backward = bool(case.get("backward", False))
    rng = np.random.default_rng(int(case.get("seed", 0)))
    arrays = [rng.standard_normal(s).astype(dtype) for s in shapes]

    # eager timing (tape on, per-op dispatch — the dygraph number)
    tensors = [to_tensor(a) for a in arrays]
    op(*tensors)  # warmup
    t_eager = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        out = op(*tensors)
        jax.block_until_ready(out.data if hasattr(out, "data") else
                              [o.data for o in out])
        t_eager.append(time.perf_counter() - t0)

    # jit timing (compiled — the deployment number)
    def f(*arrs):
        r = op(*[to_tensor(a) for a in arrs])
        return r.data if hasattr(r, "data") else [o.data for o in r]

    jf = jax.jit(f)
    jax.block_until_ready(jf(*arrays))  # compile
    t_jit = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        jax.block_until_ready(jf(*arrays))
        t_jit.append(time.perf_counter() - t0)

    rec = {"op": case["op"], "shapes": case["shapes"], "dtype": dtype,
           "repeat": repeat,
           "eager_us_median": round(statistics.median(t_eager) * 1e6, 2),
           "jit_us_median": round(statistics.median(t_jit) * 1e6, 2),
           "jit_us_min": round(min(t_jit) * 1e6, 2)}

    if backward:
        def loss(*arrs):
            r = op(*[to_tensor(a) for a in arrs])
            d = r.data if hasattr(r, "data") else r[0].data
            return (d.astype(jnp.float32) ** 2).sum()

        g = jax.jit(jax.grad(loss, argnums=tuple(range(len(arrays)))))
        jax.block_until_ready(g(*arrays))
        t_bwd = []
        for _ in range(repeat):
            t0 = time.perf_counter()
            jax.block_until_ready(g(*arrays))
            t_bwd.append(time.perf_counter() - t0)
        rec["fwd_bwd_us_median"] = round(
            statistics.median(t_bwd) * 1e6, 2)
    return rec


def main():
    ap = argparse.ArgumentParser(__doc__)
    ap.add_argument("--config", help="JSON file with a list of cases")
    ap.add_argument("--op", help="single-case op name")
    ap.add_argument("--shapes", help="comma-separated, e.g. 64x64,64x64")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--repeat", type=int, default=10)
    ap.add_argument("--backward", action="store_true")
    args = ap.parse_args()

    # device selection: probe the accelerator in a subprocess (a wedged
    # TPU tunnel must not hang the harness — same recipe as bench.py),
    # fall back to in-process CPU pinning
    import subprocess
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=120, capture_output=True)
        on_acc = probe.returncode == 0
    except subprocess.TimeoutExpired:
        on_acc = False
    if not on_acc:
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.config:
        with open(args.config) as f:
            cases = json.load(f)
    elif args.op:
        cases = [{"op": args.op, "shapes": args.shapes.split(","),
                  "dtype": args.dtype, "repeat": args.repeat,
                  "backward": args.backward}]
    else:
        ap.error("need --config or --op")
    for case in cases:
        print(json.dumps(run_case(case)))


if __name__ == "__main__":
    main()
