"""Trace ONE ResNet-50 b32 engine step on chip and print the top XLA
ops by device time (r5: the step is 15 ms / 13% MFU with convs measured
at ~full MXU throughput — find the rest).
``python tools/tpu_resnet_trace.py [batch]``."""

import collections
import gzip
import json
import pathlib
import sys
import tempfile

import numpy as np


def main():
    import jax
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    from paddle1_tpu.vision.models.resnet import resnet50
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    model = resnet50()
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())

    def loss_fn(m, b):
        return paddle.nn.functional.cross_entropy(m(Tensor(b["x"])),
                                                  Tensor(b["y"]))
    eng = ParallelEngine(model, opt, loss_fn,
                         mesh=build_mesh(dp=1, devices=[jax.devices()[0]]),
                         amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    b = eng.shard_batch(
        {"x": rng.standard_normal((batch, 3, 224, 224)).astype(np.float32),
         "y": rng.integers(0, 1000, (batch,)).astype(np.int64)})
    for _ in range(3):
        r = eng.step(b)
    np.asarray(jax.device_get(r.data if hasattr(r, "data") else r))

    td = tempfile.mkdtemp(prefix="resnet_trace_")
    with jax.profiler.trace(td):
        r = eng.step(b)
        np.asarray(jax.device_get(r.data if hasattr(r, "data") else r))
    gz = list(pathlib.Path(td).rglob("*.trace.json.gz"))
    if not gz:
        print("no trace.json.gz under", td)
        return 1
    with gzip.open(gz[0]) as f:
        tr = json.load(f)
    ev = tr["traceEvents"]
    pids, tids = {}, {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"].get("name")
    dur, cnt = collections.Counter(), collections.Counter()
    for e in ev:
        if (e.get("ph") == "X"
                and "TPU" in str(pids.get(e["pid"], ""))
                and tids.get((e["pid"], e["tid"])) == "XLA Ops"):
            dur[e["name"]] += e.get("dur", 0)
            cnt[e["name"]] += 1
    tot = sum(dur.values())
    print(f"total XLA-op device time: {tot / 1e3:.2f} ms "
          f"({len(dur)} distinct ops)")
    # group by op family (prefix before first dot/digit)
    fam = collections.Counter()
    for name, d in dur.items():
        base = name.split(".")[0].rstrip("0123456789_")
        fam[base] += d
    print("\nby family:")
    for name, d in fam.most_common(15):
        print(f"{d / 1e3:8.3f} ms {100.0 * d / tot:5.1f}%  {name[:70]}")
    print("\ntop single ops:")
    for name, d in dur.most_common(20):
        print(f"{d / 1e3:8.3f} ms {100.0 * d / tot:5.1f}% "
              f"{cnt[name]:4d}x  {name[:80]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
