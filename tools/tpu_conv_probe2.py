"""Conv probe round 2 (r5): separate tunnel-dispatch overhead from true
device conv throughput, and measure the channels-last building blocks.

The r5 first probe measured a SINGLE NHWC conv dispatch at 4.8 TF/s —
ambiguous: per-dispatch overhead through the axon relay could dominate a
~0.6 ms device op. Here every measurement chains K ops inside ONE jit so
dispatch cost is amortized K-fold:

* conv NHWC+HWIO chained        — the true device conv ceiling
* conv NHWC+OIHW chained        — does weight layout matter?
* conv NCHW chained             — the true NCHW penalty (not dispatch)
* conv+BN+relu NHWC chained     — the ResNet hot block, channels-last
* maxpool NHWC / NCHW           — reduce_window layout sensitivity
* resnet50 fwd+bwd data_format  — end-to-end, if the model supports it

Run on the real chip: ``python tools/tpu_conv_probe2.py``.
"""

import sys
import time

import numpy as np


def _slope(f, lo=2, hi=8):
    import jax
    f()
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(f())[0]))
    ts = []
    for k in (lo, hi):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = f()
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(r)[0]))
        ts.append(time.perf_counter() - t0)
    return (ts[1] - ts[0]) / (hi - lo)


def main():
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    print("device:", dev, getattr(dev, "device_kind", ""))
    K = 16  # convs chained per dispatch
    fl1 = 2 * 32 * 56 * 56 * 256 * 256 * 9  # FLOPs per conv

    rng = np.random.default_rng(0)
    x_nhwc = jnp.asarray(rng.standard_normal((32, 56, 56, 256)),
                         jnp.bfloat16)
    w_hwio = jnp.asarray(rng.standard_normal((3, 3, 256, 256)) * 0.01,
                         jnp.bfloat16)
    w_oihw = jnp.transpose(w_hwio, (3, 2, 0, 1))
    x_nchw = jnp.transpose(x_nhwc, (0, 3, 1, 2))

    def chain(conv_fn, x, w):
        def f(x, w):
            y = x
            for _ in range(K):
                y = conv_fn(y, w)
            return y
        return jax.jit(f)

    def report(name, dt, flops):
        print(f"{name}: {dt * 1e3:.2f} ms/chain "
              f"{flops / dt / 1e12:.1f} TF/s "
              f"mfu={flops / dt / 197e12:.3f}")

    c = chain(lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))), x_nhwc, w_hwio)
    report("conv NHWC+HWIO x16", _slope(lambda: c(x_nhwc, w_hwio)),
           K * fl1)

    c = chain(lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))), x_nhwc, w_oihw)
    report("conv NHWC+OIHW x16", _slope(lambda: c(x_nhwc, w_oihw)),
           K * fl1)

    c = chain(lambda x, w: jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))), x_nchw, w_oihw)
    report("conv NCHW+OIHW x16", _slope(lambda: c(x_nchw, w_oihw)),
           K * fl1)

    # the ResNet hot block channels-last: conv + scale/shift + relu
    g = jnp.ones((256,), jnp.bfloat16)
    b = jnp.zeros((256,), jnp.bfloat16)

    def block(x, w):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                x.shape, w.shape, ("NHWC", "HWIO", "NHWC")))
        return jax.nn.relu(y * g + b)
    c = chain(block, x_nhwc, w_hwio)
    report("conv+bn+relu NHWC x16", _slope(lambda: c(x_nhwc, w_hwio)),
           K * fl1)

    # grad of the chain (the backward layouts)
    def loss(x, w):
        y = x
        for _ in range(K):
            y = block(y, w)
        return jnp.sum(y.astype(jnp.float32))
    gfn = jax.jit(jax.grad(loss, argnums=(0, 1)))
    report("grad(conv+bn+relu) x16", _slope(lambda: gfn(x_nhwc, w_hwio)),
           3 * K * fl1)

    # pooling layout sensitivity (K-chained 3x3/s1 maxpool, SAME)
    def mp_nhwc(x):
        y = x
        for _ in range(K):
            y = jax.lax.reduce_window(
                y, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 1, 1, 1),
                "SAME")
        return y
    def mp_nchw(x):
        y = x
        for _ in range(K):
            y = jax.lax.reduce_window(
                y, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 1, 1),
                "SAME")
        return y
    e = 32 * 56 * 56 * 256 * K  # elements touched per chain
    f1 = jax.jit(mp_nhwc)
    dt = _slope(lambda: f1(x_nhwc))
    print(f"maxpool NHWC x16: {dt * 1e3:.2f} ms/chain "
          f"{e * 2 / dt / 1e9:.0f} GB/s eff")
    f2 = jax.jit(mp_nchw)
    dt = _slope(lambda: f2(x_nchw))
    print(f"maxpool NCHW x16: {dt * 1e3:.2f} ms/chain "
          f"{e * 2 / dt / 1e9:.0f} GB/s eff")


if __name__ == "__main__":
    sys.exit(main())
