#!/usr/bin/env python
"""API coverage diff: the reference's public python surface vs this
build (the api-diff half of the reference's CI tooling —
/root/reference/tools/check_api_compatible.py,
tools/print_signatures.py role).

Sweeps each public namespace's reference ``__all__`` (falling back to
``from X import Y`` re-exports) and classifies every name as mapped /
missing here. Prints a per-namespace table and one JSON line for
tooling; exits nonzero when coverage drops below the pinned floors so
it can gate CI like the reference's API checker.

Usage: python tools/api_diff.py [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REF = "/root/reference/python/paddle"

# namespace -> (reference source file(s), our import path, floor %)
NAMESPACES = {
    "paddle": (["__init__.py"], "paddle1_tpu", 95),
    "paddle.nn": (["nn/__init__.py"], "paddle1_tpu.nn", 90),
    "paddle.nn.functional": (["nn/functional/__init__.py"],
                             "paddle1_tpu.nn.functional", 90),
    "paddle.optimizer": (["optimizer/__init__.py"],
                         "paddle1_tpu.optimizer", 90),
    "paddle.optimizer.lr": (["optimizer/lr.py"],
                            "paddle1_tpu.optimizer.lr", 90),
    "paddle.metric": (["metric/__init__.py"], "paddle1_tpu.metric",
                      90),
    "paddle.amp": (["amp/__init__.py"], "paddle1_tpu.amp", 90),
    "paddle.static": (["static/__init__.py"], "paddle1_tpu.static",
                      70),
    "paddle.jit": (["jit/__init__.py"], "paddle1_tpu.jit", 80),
    "paddle.io": (["io/__init__.py"], "paddle1_tpu.io", 80),
    "paddle.vision.models": (["vision/models/__init__.py"],
                             "paddle1_tpu.vision.models", 80),
    "paddle.vision.ops": (["vision/ops.py"], "paddle1_tpu.vision.ops",
                          80),
    "paddle.vision.transforms": (["vision/transforms/__init__.py"],
                                 "paddle1_tpu.vision.transforms", 80),
    "paddle.distributed": (["distributed/__init__.py"],
                           "paddle1_tpu.distributed", 75),
    "paddle.distributed.fleet": (["distributed/fleet/__init__.py"],
                                 "paddle1_tpu.distributed.fleet", 70),
    "paddle.distribution": (["distribution.py"],
                            "paddle1_tpu.distribution", 70),
    "paddle.fluid.layers": (None, "paddle1_tpu.fluid.layers", 90),
}


def _ref_names(files):
    names = set()
    for f in files:
        path = os.path.join(REF, f)
        if not os.path.isfile(path):
            continue
        t = open(path, encoding="utf-8", errors="replace").read()
        # __all__ (+= extensions included) is authoritative when
        # present; the import-scan fallback would count internal
        # imports as API
        alls = re.findall(r"__all__\s*\+?=\s*\[(.*?)\]", t, re.S)
        if alls:
            for chunk in alls:
                names.update(re.findall(r"['\"]([A-Za-z_][\w]*)['\"]",
                                        chunk))
            continue
        names.update(re.findall(r"^from [\w.]+ import ([A-Za-z_]\w*)",
                                t, re.M))
        names.update(re.findall(
            r"^from [\w.]+ import \w+ as ([A-Za-z_]\w*)", t, re.M))
    return {n for n in names if not n.startswith("_")}


def _fluid_layers_names():
    names = set()
    d = os.path.join(REF, "fluid", "layers")
    for f in os.listdir(d):
        if not f.endswith(".py") or f == "__init__.py":
            continue
        t = open(os.path.join(d, f), encoding="utf-8",
                 errors="replace").read()
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", t, re.S)
        if m:
            names.update(re.findall(r"['\"]([A-Za-z_0-9]+)['\"]",
                                    m.group(1)))
    return names


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    import importlib
    rows = []
    failed = False
    for ns, (files, ours, floor) in NAMESPACES.items():
        ref = (_fluid_layers_names() if files is None
               else _ref_names(files))
        if not ref:
            continue
        try:
            mod = importlib.import_module(ours)
        except Exception as e:
            rows.append({"namespace": ns, "total": len(ref),
                         "mapped": 0, "pct": 0.0,
                         "missing": sorted(ref),
                         "error": str(e)})
            failed = True
            continue
        missing = sorted(n for n in ref if not hasattr(mod, n))
        pct = 100.0 * (len(ref) - len(missing)) / len(ref)
        if pct < floor:
            failed = True
        rows.append({"namespace": ns, "total": len(ref),
                     "mapped": len(ref) - len(missing),
                     "pct": round(pct, 1), "floor": floor,
                     "missing": missing})
    if args.json:
        print(json.dumps(rows))
    else:
        total = sum(r["total"] for r in rows)
        mapped = sum(r["mapped"] for r in rows)
        for r in rows:
            flag = " *BELOW FLOOR*" if r["pct"] < r.get("floor", 0) \
                else ""
            print(f"{r['namespace']:32s} {r['mapped']:4d}/"
                  f"{r['total']:4d}  {r['pct']:5.1f}%{flag}")
            if r["missing"] and len(r["missing"]) <= 25:
                print(f"    missing: {', '.join(r['missing'])}")
            elif r["missing"]:
                print(f"    missing ({len(r['missing'])}): "
                      f"{', '.join(r['missing'][:25])} ...")
        print(f"{'TOTAL':32s} {mapped:4d}/{total:4d}  "
              f"{100.0 * mapped / total:5.1f}%")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
