#!/usr/bin/env python
"""Shim: the metric-name lint moved into the unified suite (ISSUE 11).

The implementation (rules unchanged) lives in
``tools/lint/metric_names.py`` and runs as the ``metric-names`` pass of
``python -m tools.lint --all``. This file keeps the historical
standalone surface — ``collect``, ``check``, ``main``, the rule
constants — for existing callers and tests, and still works as a
script: ``python tools/check_metric_names.py``.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.metric_names import (  # noqa: E402 — path bootstrap first
    HIST_UNIT_SUFFIXES, HIST_UNITLESS_OK, METHODS, NAME_RE, check,
    collect, main, repo_root, target_files)

__all__ = ["HIST_UNIT_SUFFIXES", "HIST_UNITLESS_OK", "METHODS",
           "NAME_RE", "check", "collect", "main", "repo_root",
           "target_files"]

if __name__ == "__main__":
    sys.exit(main())
