#!/usr/bin/env python
"""Lint the metric-name contract (ISSUE 10 satellite).

Walks every ``.py`` under ``paddle1_tpu/`` (plus ``bench.py`` /
``bench_utils.py``) and AST-collects string-literal metric names at
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")`` call
sites, then enforces the rules the Prometheus exposition (and the
conformance test) depend on:

* **snake_case** — ``[a-z][a-z0-9_]*``: anything else breaks the
  sample-line grammar or the family prefix join;
* **counters end ``_total``** — the Prometheus counter convention
  ``rate()`` recipes assume;
* **histograms carry a unit suffix** — ``_seconds``/``_ms``/``_us``/
  ``_s``/``_per_s`` (or a known unitless family like ``_occupancy``):
  an unsuffixed latency family is a dashboard ambiguity forever;
* **no duplicate family registration across kinds** — one name must be
  exactly one of counter/gauge/histogram everywhere it appears (the
  registry also enforces this per-instance at runtime; the lint
  catches cross-module collisions before they meet in one registry).

Dynamic names (f-strings) are invisible to the lint — keep them on the
same conventions by hand (the registry's kind guard still covers them
at runtime). Exit code 0 clean, 1 with findings; wired into CI next to
check_no_bare_except.
"""

from __future__ import annotations

import ast
import os
import re
import sys

METHODS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
HIST_UNIT_SUFFIXES = ("_seconds", "_ms", "_us", "_s", "_per_s")
# unitless histogram families that are ratios/fractions by nature
HIST_UNITLESS_OK = {"batch_occupancy"}


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def target_files(root: str):
    pkg = os.path.join(root, "paddle1_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
    for fn in ("bench.py", "bench_utils.py"):
        p = os.path.join(root, fn)
        if os.path.exists(p):
            yield p


def collect(path: str):
    """Yield (kind, name, lineno) for every literal metric touch."""
    with open(path, "r") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in METHODS):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield fn.attr, arg.value, node.lineno


def check(files) -> list:
    problems = []
    kinds_by_name: dict = {}
    for path in files:
        rel = os.path.relpath(path, repo_root())
        for kind, name, lineno in collect(path):
            where = f"{rel}:{lineno}"
            if not NAME_RE.match(name):
                problems.append(
                    f"{where}: {kind} name {name!r} is not snake_case")
            if kind == "counter" and not name.endswith("_total"):
                problems.append(
                    f"{where}: counter {name!r} must end in '_total'")
            if kind in ("gauge", "histogram") and name.endswith("_total"):
                problems.append(
                    f"{where}: {kind} {name!r} must NOT end in "
                    "'_total' (that suffix promises a counter)")
            if kind == "histogram" \
                    and not name.endswith(HIST_UNIT_SUFFIXES) \
                    and name not in HIST_UNITLESS_OK:
                problems.append(
                    f"{where}: histogram {name!r} needs a unit suffix "
                    f"{HIST_UNIT_SUFFIXES} (or add it to the unitless "
                    "allowlist if it is a ratio)")
            kinds_by_name.setdefault(name, {})[kind] = where
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            sites = ", ".join(f"{k} at {w}" for k, w in sorted(
                kinds.items()))
            problems.append(
                f"metric family {name!r} registered as multiple kinds: "
                f"{sites} — one family, one kind")
    return problems


def main(argv=None) -> int:
    root = repo_root()
    problems = check(sorted(target_files(root)))
    for p in problems:
        print(p)
    if problems:
        print(f"\n{len(problems)} metric-name problem(s) "
              "(see tools/check_metric_names.py header for the rules)")
        return 1
    print("metric names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
