"""Conv probe round 4 (r5): jitter-proof timing of the conv fwd/bwd
forms. The tunnel's latency noise is additive and positive (stalls), so:

* every measured graph chains K=32 ops inside ONE jit and returns a
  SCALAR (no 25 MB readbacks);
* T(k) for k in {2, 8} calls is measured 5 times each and the MINIMUM
  is kept (the cleanest pass through the tunnel);
* per-op time = (minT(8) - minT(2)) / (6 * K).

Run on the real chip: ``python tools/tpu_conv_probe4.py``.
"""

import sys
import time

import numpy as np

K = 32


def _chain_time(f, flops_per_op):
    """f: jitted fn returning a scalar, internally chaining K ops."""
    import jax
    np.asarray(jax.device_get(f()))  # compile + warm
    mins = {}
    for k in (2, 8):
        best = None
        for _ in range(5):
            t0 = time.perf_counter()
            r = None
            for _ in range(k):
                r = f()
            np.asarray(jax.device_get(r))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        mins[k] = best
    per_op = (mins[8] - mins[2]) / (6 * K)
    return per_op, flops_per_op / per_op


def main():
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    print("device:", dev, getattr(dev, "device_kind", ""))

    N, H, W, C, O, KH = 32, 56, 56, 256, 256, 3
    fl1 = 2 * N * H * W * C * O * KH * KH
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N, H, W, C)) * 0.05,
                    jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((KH, KH, C, O)) * 0.05,
                    jnp.bfloat16)
    dy = jnp.asarray(rng.standard_normal((N, H, W, O)) * 0.05,
                     jnp.bfloat16)
    dn = lambda l, r, spec: jax.lax.conv_dimension_numbers(l, r, spec)

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=dn(x.shape, w.shape,
                                 ("NHWC", "HWIO", "NHWC")))

    def rep(name, t, tf):
        print(f"{name}: {t * 1e3:.3f} ms/op {tf / 1e12:.1f} TF/s "
              f"mfu={tf / 197e12:.3f}")

    # 1. fwd conv chain (y feeds next conv; same w)
    @jax.jit
    def fwd_chain(x, w):
        y = x
        for _ in range(K):
            y = conv(y, w)
        return jnp.sum(y.astype(jnp.float32))
    rep("fwd conv", *_chain_time(lambda: fwd_chain(x, w), fl1))

    # 2. autodiff dgrad chain: grad of the K-chain wrt x pays K dgrads
    #    (+K fwd recomputes are NOT in play: linear chain, no residuals
    #    needed for conv-only graphs — conv is bilinear, dgrad needs only
    #    w). jax grad of chain = K dgrad convs.
    @jax.jit
    def dgrad_chain(x, w):
        return jnp.sum(jax.grad(
            lambda x: jnp.sum(fwd_chain_raw(x, w).astype(jnp.float32)))(x)
            .astype(jnp.float32))

    def fwd_chain_raw(x, w):
        y = x
        for _ in range(K):
            y = conv(y, w)
        return y
    rep("autodiff dgrad (chain)",
        *_chain_time(lambda: dgrad_chain(x, w), fl1))

    # 3. plain-conv dgrad chain
    @jax.jit
    def dgrad_plain_chain(dy, w):
        wt = jnp.flip(w, (0, 1)).swapaxes(2, 3)
        y = dy
        for _ in range(K):
            y = jax.lax.conv_general_dilated(
                y, wt, (1, 1), [(1, 1), (1, 1)],
                dimension_numbers=dn(y.shape, wt.shape,
                                     ("NHWC", "HWIO", "NHWC")))
        return jnp.sum(y.astype(jnp.float32))
    rep("plain-conv dgrad",
        *_chain_time(lambda: dgrad_plain_chain(dy, w), fl1))

    # 4. autodiff wgrad chain: sum of K wgrads via grad wrt w
    @jax.jit
    def wgrad_chain(x, w):
        return jnp.sum(jax.grad(
            lambda w: jnp.sum(fwd_chain_raw(x, w).astype(jnp.float32)))(w)
            .astype(jnp.float32))
    rep("autodiff wgrad+dgrad mix (chain wrt w)",
        *_chain_time(lambda: wgrad_chain(x, w), 2 * fl1))

    # 5. plain-conv wgrad chain (fresh x each round via cheap shift to
    #    stop CSE; same compute shape)
    @jax.jit
    def wgrad_plain_chain(x, dy):
        acc = jnp.zeros((KH, KH, C, O), jnp.float32)
        xi = x
        for _ in range(K):
            lhs = jnp.transpose(xi, (3, 1, 2, 0))
            rhs = jnp.transpose(dy, (1, 2, 0, 3))
            out = jax.lax.conv_general_dilated(
                lhs, rhs, (1, 1), [(1, 1), (1, 1)],
                dimension_numbers=dn(lhs.shape, rhs.shape,
                                     ("NHWC", "HWIO", "NHWC")))
            acc = acc + jnp.transpose(out, (1, 2, 0, 3)).astype(
                jnp.float32)
            xi = xi + 1.0  # new value, same shape: defeats CSE
        return jnp.sum(acc)
    rep("plain-conv wgrad",
        *_chain_time(lambda: wgrad_plain_chain(x, dy), fl1))

    # 6. full fwd+bwd of a conv+bn+relu block chain via autodiff (what a
    #    real model pays per layer)
    g0 = jnp.ones((O,), jnp.bfloat16)

    def block(y, w):
        y = conv(y, w)
        return jax.nn.relu(y * g0)

    @jax.jit
    def block_chain_grad(x, w):
        def loss(x, w):
            y = x
            for _ in range(K):
                y = block(y, w)
            return jnp.sum(y.astype(jnp.float32))
        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        return jnp.sum(gx.astype(jnp.float32)) + jnp.sum(
            gw.astype(jnp.float32))
    rep("fwd+bwd conv+bn+relu (autodiff)",
        *_chain_time(lambda: block_chain_grad(x, w), 3 * fl1))


if __name__ == "__main__":
    sys.exit(main())
