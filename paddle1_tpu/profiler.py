"""Profiler (reference paddle/fluid/platform/profiler.* + python
fluid/profiler.py:190-314 + tools/timeline.py).

Two layers, mirroring the reference's host+device design:

* **Host spans** — ``RecordEvent`` RAII/context spans with nesting, a global
  registry, and min/max/avg aggregation tables printed by ``stop_profiler``
  (the reference's EnableProfiler/DisableProfiler tables).
* **Device timeline** — delegated to ``jax.profiler`` (XPlane/TensorBoard),
  which captures XLA execution on TPU the way CUPTI captured CUDA kernels;
  ``profiler(..., tracer_option)`` context manager starts/stops a trace dir
  viewable in TensorBoard or Perfetto.

Chrome-trace export: host spans serialize to the chrome://tracing JSON
format directly (the reference needed tools/timeline.py:115 to convert its
proto; we emit the final format)."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_tracing"]

_lock = threading.Lock()
_enabled = False
_events: List[dict] = []          # completed spans
_tls = threading.local()
_trace_dir: Optional[str] = None  # process-wide device-trace state


def _now_us() -> float:
    return time.perf_counter() * 1e6


class RecordEvent:
    """Named host span (reference platform/profiler.h:127 RecordEvent).
    Usable as context manager or begin()/end() pair."""

    def __init__(self, name: str, event_type: str = "Operator"):
        self.name = str(name) if name is not None else "<unnamed>"
        self.event_type = event_type
        self._begin = None

    def begin(self):
        if not _enabled:
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._begin = _now_us()
        stack.append(self)
        return self

    def end(self):
        if not _enabled or self._begin is None:
            return
        stack = getattr(_tls, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        ev = {"name": self.name, "type": self.event_type,
              "ts": self._begin, "dur": _now_us() - self._begin,
              "tid": threading.get_ident(),
              "depth": len(stack)}
        with _lock:
            _events.append(ev)
        self._begin = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


def reset_profiler():
    global _events
    with _lock:
        _events = []


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   log_dir: Optional[str] = None):
    """Enable host-span recording; with a log_dir also start the device
    (XLA) trace (reference profiler.py:190 start_profiler)."""
    global _enabled
    reset_profiler()
    _enabled = True
    if log_dir:
        import jax
        jax.profiler.start_trace(log_dir)
        # module-global, NOT thread-local: jax's trace is process-wide and
        # stop may legitimately run on another thread (ADVICE r1 finding)
        global _trace_dir
        _trace_dir = log_dir


def stop_profiler(sorted_key: str = "total",
                  profile_path: Optional[str] = None):
    """Stop, aggregate, print the event table; optionally write chrome
    trace JSON (reference profiler.py:260 stop_profiler)."""
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir is not None:
        import jax
        jax.profiler.stop_trace()
        _trace_dir = None
    with _lock:
        events = list(_events)
    agg: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        agg[ev["name"]].append(ev["dur"])
    rows = []
    for name, durs in agg.items():
        rows.append((name, len(durs), sum(durs), sum(durs) / len(durs),
                     min(durs), max(durs)))
    key_idx = {"total": 2, "calls": 1, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key, 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Ave(us)':>12}"
              f"{'Min(us)':>12}{'Max(us)':>12}")
        for r in rows:
            print(f"{r[0]:<40}{r[1]:>8}{r[2]:>14.1f}{r[3]:>12.1f}"
                  f"{r[4]:>12.1f}{r[5]:>12.1f}")
    if profile_path:
        export_chrome_tracing(profile_path, events)
    return rows


def export_chrome_tracing(path: str, events: Optional[List[dict]] = None):
    """Write chrome://tracing JSON (the reference's timeline.py output)."""
    if events is None:
        with _lock:
            events = list(_events)
    trace = {"traceEvents": [
        {"name": ev["name"], "cat": ev["type"], "ph": "X",
         "ts": ev["ts"], "dur": ev["dur"], "pid": os.getpid(),
         "tid": ev["tid"]}
        for ev in events]}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None,
             tracer_option: str = "Default",
             log_dir: Optional[str] = None):
    """Context manager (reference fluid/profiler.py:314 profiler)."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
