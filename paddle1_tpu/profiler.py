"""Profiler (reference paddle/fluid/platform/profiler.* + python
fluid/profiler.py:190-314 + tools/timeline.py).

Two layers, mirroring the reference's host+device design:

* **Host spans** — ``RecordEvent`` RAII/context spans with nesting, a global
  registry, and min/max/avg aggregation tables printed by ``stop_profiler``
  (the reference's EnableProfiler/DisableProfiler tables).
* **Device timeline** — delegated to ``jax.profiler`` (XPlane/TensorBoard),
  which captures XLA execution on TPU the way CUPTI captured CUDA kernels;
  ``profiler(..., tracer_option)`` context manager starts/stops a trace dir
  viewable in TensorBoard or Perfetto.

Chrome-trace export: host spans serialize to the chrome://tracing JSON
format directly (the reference needed tools/timeline.py:115 to convert its
proto; we emit the final format).

Cross-process identity (ISSUE 10): when the ``obs_trace_dir`` flag is
set, every completed span is ALSO appended to the process's
``spans-<pid>.jsonl`` sink with trace_id/span_id/parent context from
:mod:`paddle1_tpu.obs.trace` — spans record in that mode even while the
aggregation tables are off, so a serving replica can trace requests
without paying for the profiler's event list. The per-process JSONL
files merge into one cross-process chrome trace via
``obs.trace.export_chrome_trace``."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .obs import trace as obs_trace

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "export_chrome_tracing"]

_lock = threading.Lock()
_enabled = False
_events: List[dict] = []          # completed spans
_tls = threading.local()
_trace_dir: Optional[str] = None  # process-wide device-trace state


def _now_us() -> float:
    return time.perf_counter() * 1e6


class RecordEvent:
    """Named host span (reference platform/profiler.h:127 RecordEvent).
    Usable as context manager or begin()/end() pair. ``args`` ride the
    span into the chrome-trace export and the cross-process sink (e.g.
    the decode engine tags slot occupancy)."""

    def __init__(self, name: str, event_type: str = "Operator",
                 args: Optional[dict] = None):
        self.name = str(name) if name is not None else "<unnamed>"
        self.event_type = event_type
        self.args = args
        self._begin = None
        self._wall = None
        self._span_id = None
        self._trace = None

    def begin(self):
        tracing = obs_trace.sink_active()
        if not _enabled and not tracing:
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if tracing:
            # capture identity at begin: parent = innermost open span on
            # this thread, else the ambient context (wire/env-seeded)
            parent = stack[-1] if stack else None
            if parent is not None and parent._span_id is not None:
                self._trace = (parent._trace[0], parent._span_id) \
                    if parent._trace else None
            else:
                self._trace = obs_trace.current()
            self._span_id = obs_trace.new_span_id()
            self._wall = time.time()
        self._begin = _now_us()
        stack.append(self)
        return self

    def end(self):
        if self._begin is None:
            return
        # Stack maintenance happens UNCONDITIONALLY: stop_profiler
        # flipping _enabled mid-span used to early-return here and
        # leave the span on _tls.stack forever, mis-nesting every
        # later span on the thread (ISSUE 10 satellite).
        stack = getattr(_tls, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # mis-paired end() calls: drop it anyway
            stack.remove(self)
        dur_us = _now_us() - self._begin
        if _enabled:
            ev = {"name": self.name, "type": self.event_type,
                  "ts": self._begin, "dur": dur_us,
                  "tid": threading.get_ident(),
                  "depth": len(stack)}
            if self.args:
                ev["args"] = dict(self.args)
            with _lock:
                _events.append(ev)
        if self._span_id is not None:
            obs_trace.record_span(
                self.name, dur_us / 1e6, ctx=self._trace,
                span_id=self._span_id, cat=self.event_type,
                args=self.args,
                end_time=(self._wall + dur_us / 1e6
                          if self._wall is not None else None))
            self._span_id = self._trace = self._wall = None
        self._begin = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


def reset_profiler():
    global _events
    with _lock:
        _events = []


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   log_dir: Optional[str] = None):
    """Enable host-span recording; with a log_dir also start the device
    (XLA) trace (reference profiler.py:190 start_profiler). ``log_dir``
    None falls back to the ``profiler_trace_dir`` flag (empty keeps the
    device trace off)."""
    global _enabled
    reset_profiler()
    _enabled = True
    if log_dir is None:
        from .core import flags as core_flags
        log_dir = core_flags.flag("profiler_trace_dir") or None
    if log_dir:
        import jax
        jax.profiler.start_trace(log_dir)
        # module-global, NOT thread-local: jax's trace is process-wide and
        # stop may legitimately run on another thread (ADVICE r1 finding)
        global _trace_dir
        _trace_dir = log_dir


def stop_profiler(sorted_key: str = "total",
                  profile_path: Optional[str] = None):
    """Stop, aggregate, print the event table; optionally write chrome
    trace JSON (reference profiler.py:260 stop_profiler)."""
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir is not None:
        import jax
        jax.profiler.stop_trace()
        _trace_dir = None
    with _lock:
        events = list(_events)
    agg: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        agg[ev["name"]].append(ev["dur"])
    rows = []
    for name, durs in agg.items():
        rows.append((name, len(durs), sum(durs), sum(durs) / len(durs),
                     min(durs), max(durs)))
    key_idx = {"total": 2, "calls": 1, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key, 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Ave(us)':>12}"
              f"{'Min(us)':>12}{'Max(us)':>12}")
        for r in rows:
            print(f"{r[0]:<40}{r[1]:>8}{r[2]:>14.1f}{r[3]:>12.1f}"
                  f"{r[4]:>12.1f}{r[5]:>12.1f}")
    if profile_path:
        export_chrome_tracing(profile_path, events)
    return rows


def export_chrome_tracing(path: str, events: Optional[List[dict]] = None):
    """Write chrome://tracing JSON (the reference's timeline.py output)."""
    if events is None:
        with _lock:
            events = list(_events)
    trace = {"traceEvents": [
        {"name": ev["name"], "cat": ev["type"], "ph": "X",
         "ts": ev["ts"], "dur": ev["dur"], "pid": os.getpid(),
         "tid": ev["tid"],
         **({"args": ev["args"]} if ev.get("args") else {})}
        for ev in events]}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None,
             tracer_option: str = "Default",
             log_dir: Optional[str] = None):
    """Context manager (reference fluid/profiler.py:314 profiler)."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
