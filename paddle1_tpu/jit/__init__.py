"""Compiled ("static") execution: to_static / jit.save / jit.load.

Analog of the reference's dygraph→static stack
(/root/reference/python/paddle/fluid/dygraph/jit.py:161 declarative,
dygraph_to_static/program_translator.py:58 ConcreteProgram cache,
jit.py:508 save → TranslatedLayer).

The architectural inversion (SURVEY §7): the reference AST-rewrites Python
into a ProgramDesc interpreted op-by-op; on TPU we *trace* the same eager code
under jax.jit into one XLA program. Python control flow is resolved at trace
time (the supported subset matches what the reference's AST transformer
handled for non-tensor-dependent control flow); tensor-dependent control flow
should use lax.cond/scan via paddle1_tpu.static.nn.cond/while_loop.

``StaticFunction.__call__`` stays differentiable in eager mode: the whole
compiled program is recorded on the tape as ONE op whose vjp is the XLA-
compiled backward — so "static" training composes with eager autograd the
way run_program_op does in the reference.
"""

from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..autograd import engine
from ..core import dtype as dtypes
from ..core.generator import next_key, rng_scope
from ..core.tensor import Parameter, Tensor, to_tensor
from ..core.errors import InvalidArgumentError
from ..nn.layer_base import Layer

__all__ = ["to_static", "not_to_static", "InputSpec", "StaticFunction",
           "save", "load", "TranslatedLayer", "ignore_module"]


class InputSpec:
    """Shape/dtype signature (reference static/input.py InputSpec).
    A None dim means polymorphic (one recompile per concrete value)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


def _tree_map_tensors(obj, fn):
    if isinstance(obj, Tensor):
        return fn(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_map_tensors(o, fn) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_map_tensors(v, fn) for k, v in obj.items()}
    return obj


def _collect_tensors(obj, out: list):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _collect_tensors(o, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_tensors(v, out)


class StaticFunction:
    """The traced-and-compiled callable (ConcreteProgram analog; jax.jit
    owns the per-signature cache the reference kept in
    program_translator.py:133)."""

    def __init__(self, fn: Callable, input_spec=None, layer: Optional[Layer]
                 = None, donate_params: bool = False):
        from ..core.flags import flag
        if flag("dy2static") and not getattr(fn, "__not_to_static__", False):
            # AST fallback: tensor-dependent if/while/for-range lower to
            # lax.cond/while_loop instead of tripping the teaching error
            # (reference dygraph_to_static; see jit/dy2static.py)
            from . import dy2static
            fn = dy2static.convert_control_flow(fn)
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        functools.update_wrapper(self, fn,
                                 assigned=("__name__", "__doc__"),
                                 updated=())
        self._jitted = jax.jit(self._pure, static_argnames=("training",))

    # pure(params_dict, key, *input_arrays) -> output arrays
    def _pure(self, params, key, args, kwargs, training=False):
        layer = self._layer

        def run():
            with engine.no_grad(), rng_scope(key):
                a = _tree_map_tensors(
                    args, lambda arr: arr)  # already arrays→wrapped below
                wrapped_args = _tree_map_tensors(
                    args, lambda x: x)
                t_args = _rewrap(args)
                t_kwargs = _rewrap(kwargs)
                out = self._fn(*t_args, **t_kwargs)
                return _tree_map_tensors(out, lambda t: t.data)
        if layer is not None:
            was_training = layer.training
            layer.training = training
            try:
                with layer.load_functional_state(params):
                    return run()
            finally:
                layer.training = was_training
        return run()

    def __call__(self, *args, **kwargs):
        params = self._layer.functional_state() if self._layer is not None \
            else {}
        key = next_key()
        arr_args = _tree_map_tensors(args, lambda t: t.data)
        arr_kwargs = _tree_map_tensors(kwargs, lambda t: t.data)
        training = self._layer.training if self._layer is not None else False

        param_tensors = (list(self._layer.state_dict().values())
                         if self._layer is not None else [])
        needs_grad = engine.is_grad_enabled() and any(
            not p.stop_gradient for p in param_tensors)

        input_tensors = []
        _collect_tensors(args, input_tensors)
        _collect_tensors(kwargs, input_tensors)
        needs_grad = needs_grad or (engine.is_grad_enabled() and any(
            not t.stop_gradient for t in input_tensors))

        names = list(params.keys())

        def op_fn(*flat):
            p = dict(zip(names, flat[:len(names)]))
            in_flat = flat[len(names):]
            rebuilt_args = _rebuild(arr_args, list(in_flat[:_count(arr_args)]))
            rebuilt_kwargs = _rebuild(
                arr_kwargs, list(in_flat[_count(arr_args):]))
            return self._jitted(p, key, rebuilt_args, rebuilt_kwargs,
                                training=training)

        flat_inputs = (param_tensors +
                       input_tensors)
        try:
            out = engine.apply(
                f"static:{getattr(self._fn, '__name__', 'fn')}",
                op_fn, tuple(flat_inputs))
        except jax.errors.ConcretizationTypeError as e:
            # covers TracerBoolConversionError too (its subclass)
            # The reference rewrites `if tensor:` / tensor-bounded loops
            # via its AST transformer (fluid/dygraph/dygraph_to_static/).
            # This build is trace-based by design (SURVEY §7), so
            # tensor-dependent Python control flow must be expressed with
            # the graph-native primitives — teach, loudly, instead of
            # surfacing a raw tracer error.
            fn_name = getattr(self._fn, "__name__", "fn")
            raise InvalidArgumentError(
                f"to_static: `{fn_name}` uses a Tensor's VALUE in Python "
                f"control flow (`if tensor:` / `while tensor:` / "
                f"`tensor.item()`), which cannot be traced into a static "
                f"program. Rewrite that branch with "
                f"paddle1_tpu.static.nn.cond / case / switch_case, the "
                f"loop with paddle1_tpu.static.nn.while_loop, or move the "
                f"decision out of the compiled function (compute it "
                f"eagerly and pass the result in). Original trace error: "
                f"{type(e).__name__}: {e}") from e
        return out

    @property
    def concrete_program(self):
        return self._jitted

    def lower(self, *args, **kwargs):
        params = self._layer.functional_state() if self._layer else {}
        key = jax.random.key(0)
        arr_args = _tree_map_tensors(args, lambda t: t.data)
        return self._jitted.lower(params, key, arr_args, {}, training=False)

    def program_text(self, *args) -> str:
        """The traced program as StableHLO MLIR text — the program
        INSPECTION surface (reference: printing the ProgramDesc /
        main_program of a to_static function). Transformation stays
        XLA's job; inspection is the part users actually need."""
        return self.lower(*args).as_text()


def _count(tree) -> int:
    out = []
    _collect_arrays(tree, out)
    return len(out)


def _collect_arrays(obj, out):
    if isinstance(obj, (jax.Array, np.ndarray)) or hasattr(obj, "dtype"):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _collect_arrays(o, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_arrays(v, out)


def _rebuild(template, flat: list):
    if isinstance(template, (jax.Array, np.ndarray)) or (
            hasattr(template, "dtype") and hasattr(template, "shape")):
        return flat.pop(0)
    if isinstance(template, tuple):
        return tuple(_rebuild(t, flat) for t in template)
    if isinstance(template, list):
        return [_rebuild(t, flat) for t in template]
    if isinstance(template, dict):
        return {k: _rebuild(v, flat) for k, v in template.items()}
    return template


def _rewrap(obj):
    """arrays → Tensors so the traced eager code sees Tensor inputs."""
    if isinstance(obj, (jax.Array, np.ndarray)) or (
            hasattr(obj, "dtype") and hasattr(obj, "shape") and
            not isinstance(obj, Tensor)):
        return Tensor(obj, stop_gradient=True)
    if isinstance(obj, tuple):
        return tuple(_rewrap(o) for o in obj)
    if isinstance(obj, list):
        return [_rewrap(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _rewrap(v) for k, v in obj.items()}
    return obj


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@to_static decorator / converter (reference jit.py:161)."""

    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            fn.forward = sf
            return fn
        # plain function (may be a bound Layer.forward)
        layer = getattr(fn, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(fn, input_spec, layer=layer)
        return StaticFunction(fn, input_spec, layer=None)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def ignore_module(modules):
    return None


# ---------------------------------------------------------------------------
# jit.save / jit.load — deployable program+params artifact
# ---------------------------------------------------------------------------


def save(layer, path, input_spec=None, **configs):
    """Persist a Layer as {path}.pdmodel (serialized StableHLO via
    jax.export) + {path}.pdiparams (reference jit.py:508 saves ProgramDesc +
    params). The exported artifact runs without the Python model class —
    the TranslatedLayer analog."""
    from jax import export as jexport
    if isinstance(layer, StaticFunction):
        sf = layer
        base_layer = sf._layer
    elif isinstance(layer, Layer):
        fwd = layer.forward
        sf = fwd if isinstance(fwd, StaticFunction) else StaticFunction(
            fwd if not isinstance(fwd, StaticFunction) else fwd._fn,
            input_spec, layer=layer)
        base_layer = layer
    else:
        raise InvalidArgumentError("jit.save expects a Layer or "
                                   "StaticFunction")
    if input_spec is None:
        raise InvalidArgumentError(
            "jit.save requires input_spec on TPU (shapes must be known "
            "to export StableHLO)")
    params = base_layer.functional_state() if base_layer is not None else {}

    key = jax.random.key(0)
    specs = [jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
             for s in input_spec]

    def infer_fn(params, *inputs):
        return sf._pure(params, key, tuple(inputs), {}, training=False)

    param_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params.items()}
    exported = jexport.export(jax.jit(infer_fn))(param_specs, *specs)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    from ..framework.io import save as fsave
    fsave({k: to_tensor(np.asarray(v)) for k, v in params.items()},
          path + ".pdiparams")
    # feed/fetch metadata sidecar for the inference Predictor (the
    # reference stores feed/fetch ops inside the ProgramDesc; StableHLO
    # has positional args, so names ride alongside)
    import json as _json
    from ..framework import op_version as _opv
    probe = exported.out_avals
    meta = {"inputs": [{"name": s.name or f"input_{i}",
                        "shape": list(s.shape),
                        "dtype": str(np.dtype(s.dtype))}
                       for i, s in enumerate(input_spec)],
            "n_outputs": len(probe) if isinstance(probe, (list, tuple))
            else 1,
            # artifact/op compat block (reference op_version_registry):
            # loaders refuse newer-runtime artifacts, warn on older
            "compat": _opv.snapshot()}
    with open(path + ".pdconfig", "w") as f:
        _json.dump(meta, f)


class TranslatedLayer(Layer):
    """Deserialized inference program (reference TranslatedLayer:
    jit.py:844 load). Parameters are restored so state_dict works; forward
    invokes the deserialized XLA program."""

    def __init__(self, exported, params: Dict[str, Any]):
        super().__init__()
        self._exported = exported
        self._params_arrays = params
        for k, v in params.items():
            safe = k.replace(".", "__")
            self.add_parameter(safe, Parameter(v, name=k))

    def forward(self, *inputs):
        arrs = [i.data if isinstance(i, Tensor) else np.asarray(i)
                for i in inputs]
        params = {p.name: p.data for p in self.parameters()}
        out = self._exported.call(params, *arrs)
        return _tree_map_tensors_from_arrays(out)

    def program(self) -> str:
        """Deserialized program as StableHLO MLIR text (reference: a
        loaded inference program's desc is inspectable)."""
        return str(self._exported.mlir_module())


def _tree_map_tensors_from_arrays(obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_map_tensors_from_arrays(o) for o in obj)
    return to_tensor(obj)


def load(path, **configs) -> TranslatedLayer:
    import json as _json
    from jax import export as jexport
    from ..framework import op_version as _opv
    saved = None
    try:
        with open(path + ".pdconfig") as f:
            saved = _json.load(f).get("compat")
    except (OSError, ValueError):
        pass  # sidecar optional; check_compat warns on None
    _opv.check_compat(saved, source=f"jit artifact {path!r}")
    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    from ..framework.io import load as fload
    params = fload(path + ".pdiparams", return_numpy=True)
    return TranslatedLayer(exported, params)


# -- reference jit misc surface (dygraph/jit.py, ProgramTranslator) ----------

declarative = to_static  # the 1.x spelling (jit.py:161)

_code_level = 0
_verbosity = 0


def set_code_level(level=100):
    """Reference dy2static logging knob: records the level (transformed
    code is visible via StaticFunction.code here)."""
    global _code_level
    _code_level = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    global _verbosity
    _verbosity = int(level)


class ProgramTranslator:
    """Singleton switch for dy2static conversion (reference
    dygraph/dygraph_to_static/program_translator.py:795). ``enable``
    maps onto the engine's dy2static flag."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        from ..core.flags import set_flags
        set_flags({"dy2static": bool(enable_to_static)})

    def get_code(self, dygraph_func):
        fn = to_static(dygraph_func)
        return getattr(fn, "code", None)


class TracedLayer:
    """Reference dygraph/jit.py TracedLayer: trace a layer once and
    replay the static form. Here tracing IS jit: ``trace`` wraps the
    layer in to_static and runs it once to build the cache."""

    def __init__(self, fn, example_inputs):
        self._fn = fn
        self._inputs = example_inputs

    @staticmethod
    def trace(layer, inputs):
        fn = to_static(layer)
        outs = fn(*inputs)
        return outs, TracedLayer(fn, inputs)

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._fn, path, input_spec=list(self._inputs))


__all__ += ["declarative", "set_code_level", "set_verbosity",
            "ProgramTranslator", "TracedLayer"]
