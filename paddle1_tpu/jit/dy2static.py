"""AST fallback for tensor-dependent Python control flow under to_static.

Reference analog: the dygraph→static AST rewriters in
python/paddle/fluid/dygraph/dygraph_to_static/ (ifelse_transformer.py,
loop_transformer.py, logical_transformer.py, convert_operators.py —
8.9k LoC). This build keeps the reference's *runtime-dispatch* design:
each ``if``/``while``/``for range()`` statement is rewritten to call a
converter that executes plain Python when the condition is concrete and
lowers to ``lax.cond`` / ``lax.while_loop`` when it is a traced Tensor.

TPU-first scoping (SURVEY §7): tracing already handles everything except
value-dependent control flow, so ONLY control flow is rewritten — no
name mangling of the rest of the function, no program-desc construction.
Inside a to_static trace the tape is disabled (StaticFunction._pure runs
under engine.no_grad()) and autodiff is JAX's own over the traced ops,
so the converters may close over traced Tensors freely; lax.cond/
while_loop closure conversion keeps gradients correct.

Scope (documented limits, each guarded by a loud teaching error or a
clean fallback to the untransformed statement):

* ``if`` / ``while`` / ``for .. in range(..)`` whose body has no
  ``break`` / ``continue`` / ``yield`` are converted; EARLY ``return``
  converts too (r4): an ``if`` whose body tail-returns absorbs the rest
  of the function as its else-branch (single-exit normalization, the
  reference return_transformer idea) and all-paths-return ``if``s
  become a ``lax.cond`` over the return values
  (:func:`convert_ifelse_return`). Loop-exit statements stay plain
  Python (correct for concrete conditions; a traced condition there
  still raises the teaching error from StaticFunction).
* ``a and b`` / ``a or b`` / ``not a`` are rewritten to converters that
  preserve Python value semantics (incl. short-circuit) for concrete
  operands and compute ``logical_and/or/not`` for traced ones.
* Calls inside a converted function are routed through
  :func:`convert_call` (the reference's convert_call,
  dygraph_to_static/convert_call_func.py): plain user-defined Python
  functions are recursively converted (cached); builtins, library code
  (paddle1_tpu/jax/numpy/stdlib), classes, and anything marked
  ``@not_to_static`` pass through untouched.
* Functions using ``global``/``nonlocal``, or whose source is
  unavailable (REPL/exec/lambda), fall back to the original unchanged.
* A ``while``/``for`` whose bound is CONCRETE unrolls under the trace
  (plain Python), so it stays reverse-differentiable; a traced bound
  lowers to ``lax.while_loop``, which XLA cannot reverse-differentiate —
  value/inference paths work, `.backward()` through such a loop raises
  JAX's while-autodiff error (same shape as the reference's
  while_grad-unsupported cases). For a TRAINABLE dynamic loop, call
  ``static.nn.while_loop(cond, body, vars, max_iter=N)`` directly — the
  bounded lax.scan lowering freezes the state once the condition goes
  false and stays reverse-differentiable.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..core.errors import InvalidArgumentError
from ..core.tensor import Tensor

__all__ = ["convert_control_flow", "convert_ifelse", "convert_while",
           "convert_logical_and", "convert_logical_or",
           "convert_logical_not", "range_test", "UNDEF"]


class _Undef:
    """Sentinel bound to names that MIGHT be assigned by a branch/loop but
    are unbound at its entry (the reference's UndefinedVar,
    dygraph_to_static/utils.py). Any USE of the sentinel raises the same
    UnboundLocalError plain Python would have raised at that point, naming
    the variable — it must not flow silently into downstream math."""

    __slots__ = ("name", "hint")

    def __init__(self, name: str = "<var>"):
        self.name = name
        self.hint = ""

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            f"local variable '{self.name}' referenced before assignment "
            f"(it is only bound on a branch/loop path that did not run; "
            f"dy2static preserved Python's unbound semantics)"
            + (f" — {self.hint}" if self.hint else ""))

    def __repr__(self):
        return f"<undefined {self.name}>"

    # every common interaction surfaces the error at the use site
    __bool__ = __len__ = __iter__ = __call__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __matmul__ = __rmatmul__ = __neg__ = __abs__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = _raise
    __getitem__ = __contains__ = __float__ = __int__ = _raise

    def __getattr__(self, item):
        self._raise()


UNDEF = _Undef()


def _raw(x):
    return x.data if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return isinstance(_raw(x), jax.core.Tracer)


def _to_bool(x) -> bool:
    return bool(_raw(x))


def _wrap_like(template, value):
    """Re-wrap a branch output as Tensor iff the user-side value was one."""
    return Tensor(value) if isinstance(template, Tensor) else value


# ---------------------------------------------------------------------------
# runtime converters (reference: dygraph_to_static/convert_operators.py)
# ---------------------------------------------------------------------------

class _BranchError(Exception):
    """Carrier for a TypeError raised by USER branch code — it must
    escape the except-TypeError around lax.cond, which is only for the
    cond's own branch-structure mismatch."""

    def __init__(self, exc):
        self.exc = exc


def _cond_dispatch(pred, branch_t, branch_f, mismatch_msg):
    """lax.cond over two wrapped branches, disambiguating user
    TypeErrors from cond structure mismatches (shared by convert_ifelse
    and convert_ifelse_return)."""
    try:
        return jax.lax.cond(jnp.reshape(_raw(pred), ()).astype(bool),
                            branch_t, branch_f, 0)
    except _BranchError as be:
        raise be.exc
    except TypeError as e:
        raise InvalidArgumentError(mismatch_msg + f" ({e})") from e


def convert_ifelse(pred, true_fn, false_fn, init, names: Sequence[str],
                   in_true: Sequence[bool], in_false: Sequence[bool]):
    """``if`` dispatch. true_fn/false_fn take the current values of
    ``names`` (every name assigned in either branch; UNDEF when unbound)
    and return their values at branch exit. ``in_true``/``in_false`` mark
    which names each branch ASSIGNS (known statically by the AST rewrite).

    Traced path: names defined on both sides (assigned there, or already
    bound before the `if`) flow through a real ``lax.cond`` — the branch
    callbacks run INSIDE the cond, so only the taken branch executes on
    device. One-sided names are excluded from the cond and come back as
    named sentinels that raise at their (ill-defined) use site."""
    if not _is_traced(pred):
        return true_fn(*init) if _to_bool(pred) else false_fn(*init)

    bound = [not isinstance(v, _Undef) for v in init]
    both = [(t or b) and (f or b)
            for t, f, b in zip(in_true, in_false, bound)]
    keep = [i for i, ok in enumerate(both) if ok]
    templates = {}

    def _branch(fn, key):
        def inner(_):
            try:
                outs = fn(*init)
            except TypeError as ue:
                raise _BranchError(ue) from ue
            templates[key] = outs
            return tuple(jnp.asarray(_raw(outs[i])) for i in keep)
        return inner

    kept = _cond_dispatch(
        pred, _branch(true_fn, "t"), _branch(false_fn, "f"),
        f"to_static: the branches of a Tensor-condition `if` produce "
        f"mismatched shapes/dtypes for {list(names)} — a traced branch "
        f"must yield the same structure on both sides.")
    tmpl = templates.get("t") or templates.get("f")
    out, ki = [], 0
    for i, name in enumerate(names):
        if both[i]:
            out.append(_wrap_like(tmpl[i], kept[ki]))
            ki += 1
        else:
            u = _Undef(name)
            u.hint = ("under a Tensor-condition `if`, a variable must be "
                      "assigned in BOTH branches (or initialized before "
                      "the `if`) to be readable afterwards")
            out.append(u)
    return tuple(out)


def convert_ifelse_return(pred, true_fn, false_fn):
    """Early-return ``if`` dispatch: both branch closures RETURN from the
    enclosing function (the AST pass proved every path through them ends
    in ``return``), so unlike :func:`convert_ifelse` no locals flow out —
    the branches' return VALUES are the whole contract. Traced predicate
    → ``lax.cond`` over the two return values (same pytree structure
    required, like the reference's RETURN-transformer path in
    dygraph_to_static/return_transformer.py)."""
    if not _is_traced(pred):
        return true_fn() if _to_bool(pred) else false_fn()

    templates = {}
    _is_tensor = lambda v: isinstance(v, Tensor)

    def _branch(fn, key):
        def inner(_):
            try:
                out = fn()
            except TypeError as ue:
                raise _BranchError(ue) from ue
            templates[key] = out
            leaves, _ = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor)
            return tuple(jnp.asarray(_raw(v)) for v in leaves)
        return inner

    msg = ("to_static: a Tensor-condition `if` where both paths RETURN "
           "must return the same structure (shapes/dtypes/pytree) on "
           "both sides.")
    kept = _cond_dispatch(pred, _branch(true_fn, "t"),
                          _branch(false_fn, "f"), msg)
    # equal LEAF structure got past lax.cond; the PYTREE structure
    # (tuple-vs-list, grouping) must match too — silently imposing the
    # true branch's shape would be wrong data, not an error
    td_t = jax.tree_util.tree_structure(templates["t"], is_leaf=_is_tensor)
    td_f = jax.tree_util.tree_structure(templates["f"], is_leaf=_is_tensor)
    if td_t != td_f:
        raise InvalidArgumentError(msg + f" (true branch returns {td_t}, "
                                         f"false branch {td_f})")
    tmpl = templates["t"]
    leaves, treedef = jax.tree_util.tree_flatten(tmpl, is_leaf=_is_tensor)
    rebuilt = [_wrap_like(t, k) for t, k in zip(leaves, kept)]
    return jax.tree_util.tree_unflatten(treedef, rebuilt)


def convert_while(test_fn, body_fn, init, names: Sequence[str],
                  needs_init: Optional[Sequence[bool]] = None):
    """``while`` dispatch. test_fn/body_fn take the values of ``names``
    (every name assigned in the loop body); body_fn returns their values
    at iteration exit. ``needs_init[i]`` marks names whose PRE-iteration
    value is observable (read in the test, or read before written in the
    body) — statically computed by the AST rewrite; per-iteration
    temporaries (write-first) carry a dead input and need no init."""
    vals = list(init)
    probe = test_fn(*vals)
    if not _is_traced(probe):
        # concrete bound: plain Python — under a trace this UNROLLS the
        # loop (traced carries are fine), which also keeps reverse-mode
        # autodiff working; XLA cannot reverse-differentiate a dynamic
        # while_loop, so the unrolled form is strictly more capable here.
        # The probe IS the first test result (a side-effecting test must
        # run exactly once per state).
        while _to_bool(probe):
            vals = list(body_fn(*vals))
            probe = test_fn(*vals)
        return tuple(vals)

    if needs_init is None:
        needs_init = [True] * len(names)
    undef_ix = [i for i, v in enumerate(vals) if isinstance(v, _Undef)]
    for i in undef_ix:
        if needs_init[i]:
            raise InvalidArgumentError(
                f"to_static: `{names[i]}` is read by a Tensor-condition "
                f"`while` (in its test, or before being assigned in the "
                f"body) but is unbound at loop entry. Initialize "
                f"`{names[i]}` before the loop (e.g. "
                f"`{names[i]} = paddle.zeros(...)`).")

    def b(flat):
        outs = body_fn(*(_wrap_like(t, v) for t, v in zip(vals, flat)))
        return tuple(jnp.asarray(_raw(o)) for o in outs)

    if undef_ix:
        # write-first temporaries: their carry INPUT is dead, but
        # lax.while_loop still needs a structure-matching seed. Discover
        # each one's per-iteration structure via eval_shape (emits no
        # ops — safe exactly because the placeholder is never read).
        placeholder = jnp.zeros((), jnp.float32)
        probe_flat = [placeholder if isinstance(v, _Undef)
                      else jnp.asarray(_raw(v)) for v in vals]
        shapes = jax.eval_shape(lambda *fl: b(fl), *probe_flat)
        for i in undef_ix:
            vals[i] = jnp.zeros(shapes[i].shape, shapes[i].dtype)

    def c(flat):
        out = test_fn(*(_wrap_like(t, v) for t, v in zip(vals, flat)))
        return jnp.reshape(_raw(out), ()).astype(bool)

    flat0 = tuple(jnp.asarray(_raw(v)) for v in vals)
    try:
        outs = jax.lax.while_loop(c, b, flat0)
    except TypeError as e:
        raise InvalidArgumentError(
            f"to_static: a Tensor-condition `while` changes the "
            f"shape/dtype of its loop variables {list(names)} across "
            f"iterations — carried state must keep a fixed structure. "
            f"({e})") from e
    return tuple(_wrap_like(t, o) for t, o in zip(vals, outs))


def for_seed(it, stop, step, name):
    """Pre-loop value for the USER's for-range variable. Concrete range:
    the unbound sentinel (Python leaves the var unbound until the first
    iteration). Traced range: lax.while_loop needs a uniform carry, so
    seed with the counter's start — a dead value, the body assigns the
    variable before any read."""
    if _is_traced(it) or _is_traced(stop) or _is_traced(step):
        return it
    return _Undef(name)


def range_test(i, stop, step):
    """``for i in range(...)`` desugars to a while; the continuation test
    depends on the sign of step (negative ranges count down)."""
    if _is_traced(i) or _is_traced(stop) or _is_traced(step):
        import paddle1_tpu.ops.math_ops  # registers Tensor operators
        return convert_logical_or(
            convert_logical_and(step > 0, lambda: i < stop),
            lambda: convert_logical_and(step < 0, lambda: i > stop))
    return (i < stop) if step > 0 else (i > stop)


def convert_logical_and(a, b_fn: Callable):
    if _is_traced(a):
        b = b_fn()
        return _wrap_like(a if isinstance(a, Tensor) else b,
                          jnp.logical_and(jnp.asarray(_raw(a), bool),
                                          jnp.asarray(_raw(b), bool)))
    return a if not _to_bool(a) else b_fn()  # python value semantics


def convert_logical_or(a, b_fn: Callable):
    if _is_traced(a):
        b = b_fn()
        return _wrap_like(a if isinstance(a, Tensor) else b,
                          jnp.logical_or(jnp.asarray(_raw(a), bool),
                                         jnp.asarray(_raw(b), bool)))
    return a if _to_bool(a) else b_fn()


def convert_logical_not(a):
    if _is_traced(a):
        return _wrap_like(a, jnp.logical_not(jnp.asarray(_raw(a), bool)))
    return not _to_bool(a)


_SKIP_MODULE_PREFIXES = ("paddle1_tpu", "jax", "numpy")


def _is_library_code(fn) -> bool:
    """Only USER code converts. A denylist of module names cannot cover
    the stdlib + every third-party package (recompiling re.sub once
    crashed sre's Tokenizer), so decide by FILE LOCATION: anything under
    the interpreter's stdlib/site-packages trees — or with no file at
    all — is library code."""
    import sys
    import sysconfig
    mod = getattr(fn, "__module__", "") or ""
    if mod.split(".")[0] in _SKIP_MODULE_PREFIXES:
        return True
    f = getattr(sys.modules.get(mod), "__file__", None)
    if not f:
        return True  # builtins / frozen / synthetic modules
    paths = sysconfig.get_paths()
    roots = {paths.get("stdlib"), paths.get("platstdlib"),
             paths.get("purelib"), paths.get("platlib")}
    import os
    f = os.path.abspath(f)
    return any(r and f.startswith(os.path.abspath(r) + os.sep)
               for r in roots)


import weakref
_call_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def convert_call(fn):
    """Route a call target through conversion (reference
    convert_call_func.py convert_call): recursively convert plain
    user-defined functions so tensor control flow in HELPERS also
    lowers; leave library code, builtins, classes, callables without
    source, and ``@not_to_static`` targets untouched. Conversion
    failures fall back to the original callable (convert_control_flow's
    own contract)."""
    import types

    if type(fn) is types.FunctionType:  # the hot path
        if getattr(fn, "__not_to_static__", False) or \
                getattr(fn, "_p1t_dy2s_converted", False) or \
                _is_library_code(fn):
            return fn
        return _convert_cached(fn)
    if isinstance(fn, types.MethodType):
        if getattr(fn, "__not_to_static__", False) or \
                getattr(fn.__func__, "_p1t_dy2s_converted", False) or \
                _is_library_code(fn):
            return fn
        conv = _convert_cached(fn.__func__)
        return fn if conv is fn.__func__ else \
            types.MethodType(conv, fn.__self__)
    return fn  # classes, builtins, callables, partials: untouched


def _convert_cached(f):
    conv = _call_cache.get(f)
    if conv is None:
        conv = convert_control_flow(f)
        if conv is not f and hasattr(conv, "__wrapped__"):
            # functools.wraps back-ref would make the weak cache entry
            # immortal (value → key strong ref)
            del conv.__wrapped__
        _call_cache[f] = conv
    return conv


# ---------------------------------------------------------------------------
# AST rewrite (reference: ifelse/loop/logical transformers)
# ---------------------------------------------------------------------------

_H = "__p1t_dy2s"  # namespace prefix for injected helpers/temporaries


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list, excluding nested function/class
    scopes (their locals do not escape) and comprehension targets (own
    scope in py3)."""

    def __init__(self):
        self.names = []
        self.def_names = []

    def _add(self, target):
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                if node.id not in self.names:
                    self.names.append(node.id)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._add(node.optional_vars)

    def visit_NamedExpr(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node.name not in self.names:
            self.names.append(node.name)
        if node.name not in self.def_names:
            self.def_names.append(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        if node.name not in self.names:
            self.names.append(node.name)
        if node.name not in self.def_names:
            self.def_names.append(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ListComp(self, node):
        pass

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp


def _assigned(stmts) -> list:
    """Names bound by stmts, minus the converter's injected helper
    FUNCTIONS (nested conversions create ``__p1t_dy2s_true_*`` defs that
    must not become branch outputs). Injected value temps (for-range
    counters) DO count — they are genuine loop-carried state."""
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    helper_defs = {n for n in v.def_names if n.startswith(_H)}
    return [n for n in v.names if n not in helper_defs]


def _defines_scope(stmts) -> bool:
    """True when stmts bind a user function/class (its object cannot flow
    through lax.cond/while_loop, and hiding it inside the branch closure
    would change plain-Python visibility) — such statements stay Python."""
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return any(not n.startswith(_H) for n in v.def_names)


def _walk_scope(node):
    """ast.walk that does not descend into nested function/class scopes
    (their return/break/continue belong to the inner scope)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue  # inner scope: its return/break/yield are not ours
        stack.extend(ast.iter_child_nodes(n))


def _expr_loads(node) -> list:
    """Names loaded by an expression (not descending into inner scopes)."""
    out = []
    for n in _walk_scope(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.append(n.id)
    return out


def _read_before_write(stmts, written=None) -> set:
    """Names whose value at BLOCK ENTRY may be observed: loaded somewhere
    before this block unconditionally writes them. A linear, conservative
    approximation (nested branches contribute reads but never count as
    definite writes), so a per-iteration temporary that is written first
    is reliably classified, and anything uncertain stays 'read'."""
    written = set(written or ())
    reads = set()

    def note_reads(expr):
        for n in _expr_loads(expr):
            if n not in written:
                reads.add(n)

    for s in stmts:
        if isinstance(s, ast.Assign):
            note_reads(s.value)
            for t in s.targets:
                if isinstance(t, ast.Name):
                    written.add(t.id)
                else:
                    note_reads(t)  # x[i] = ..: reads x (and i)
        elif isinstance(s, ast.AugAssign):
            note_reads(s.value)
            if isinstance(s.target, ast.Name):
                if s.target.id not in written:
                    reads.add(s.target.id)  # x += v reads x
                written.add(s.target.id)
            else:
                note_reads(s.target)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                note_reads(s.value)
                if isinstance(s.target, ast.Name):
                    written.add(s.target.id)
        elif isinstance(s, ast.If):
            note_reads(s.test)
            reads |= _read_before_write(s.body, written)
            reads |= _read_before_write(s.orelse, written)
        elif isinstance(s, (ast.While,)):
            note_reads(s.test)
            reads |= _read_before_write(s.body, written)
            reads |= _read_before_write(s.orelse, written)
        elif isinstance(s, ast.For):
            note_reads(s.iter)
            reads |= _read_before_write(s.body, written)
            reads |= _read_before_write(s.orelse, written)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            written.add(s.name)  # the def itself; body is an inner scope
        elif isinstance(s, ast.Try):
            reads |= _read_before_write(s.body, written)
            for h in s.handlers:
                reads |= _read_before_write(h.body, written)
            reads |= _read_before_write(s.orelse, written)
            reads |= _read_before_write(s.finalbody, written)
        else:
            note_reads(s)
    return reads


def _has_walrus(expr) -> bool:
    return any(isinstance(n, ast.NamedExpr) for n in _walk_scope(expr))


def _has_early_exit(stmts) -> bool:
    """return/break/continue/yield in THIS scope makes a statement
    non-convertible (nested defs' returns don't count)."""
    for s in stmts:
        for node in _walk_scope(s):
            if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                                 ast.Yield, ast.YieldFrom)):
                return True
    return False


def _has_loop_exit_or_yield(stmts) -> bool:
    """UNSCOPED break/continue (i.e. belonging to a loop OUTSIDE these
    statements) or any yield in scope. A break/continue inside a loop
    that is itself part of ``stmts`` exits only that inner loop —
    absorbing such statements into an else-branch stays
    semantics-preserving."""
    def check(node, in_loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return False
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(node, (ast.Break, ast.Continue)) and not in_loop:
            return True
        enter_loop = in_loop or isinstance(node, (ast.While, ast.For,
                                                  ast.AsyncFor))
        return any(check(ch, enter_loop)
                   for ch in ast.iter_child_nodes(node))
    return any(check(s, False) for s in stmts)


def _ends_in_return(stmts) -> bool:
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (_ends_in_return(last.body)
                and _ends_in_return(last.orelse))
    return False


def _normalize_tail_returns(stmts):
    """Single-exit normalization (the reference's return_transformer
    idea, scoped to the tail-return pattern): an ``if`` whose body ends
    in ``return`` absorbs the REMAINDER of the statement list as its
    else-branch, so both paths return and the `if` becomes a pure
    value choice. Applied only OUTSIDE loops (inside a loop the
    remainder of the body does not end the iteration's scope)."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.If):
            body = _normalize_tail_returns(s.body)
            rest = stmts[idx + 1:]
            if (_ends_in_return(body)
                    and not _has_loop_exit_or_yield(body)
                    and not _has_loop_exit_or_yield(s.orelse)
                    and not _has_loop_exit_or_yield(rest)):
                # merge the RAW orelse with the remainder FIRST, then
                # normalize the combined list — normalizing the orelse
                # alone would close an elif's fall-through path with a
                # premature bare `return`
                merged = _normalize_tail_returns(list(s.orelse) + rest)
                if not _ends_in_return(merged):
                    merged = merged + [ast.Return(value=None)]
                new_if = ast.If(test=s.test, body=body, orelse=merged)
                ast.copy_location(new_if, s)
                ast.fix_missing_locations(new_if)
                out.append(new_if)
                return out
            s = ast.If(test=s.test, body=body,
                       orelse=_normalize_tail_returns(s.orelse))
            ast.copy_location(s, stmts[idx])
            ast.fix_missing_locations(s)
        out.append(s)
    return out


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _prebind(names):
    """``try: x\nexcept ...: x = UNDEF`` for each name — marks
    maybe-unbound names so the converters can diagnose them."""
    out = []
    for n in names:
        out.append(ast.Try(
            body=[ast.Expr(value=_load(n))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_load("NameError"),
                                     _load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[_store(n)],
                    value=ast.Call(func=_load(f"{_H}_undef"),
                                   args=[ast.Constant(value=n)],
                                   keywords=[]))])],
            orelse=[], finalbody=[]))
    return out


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


def _str_list(names):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.wrapped_calls = 0

    def visit_Call(self, node):
        self.generic_visit(node)
        # route the call target through convert_call so tensor control
        # flow inside HELPER functions converts too; skip the
        # converter's own injected helpers
        if isinstance(node.func, ast.Name) and node.func.id.startswith(_H):
            return node
        if isinstance(node.func, ast.Name) and node.func.id in (
                "super", "range", "len", "isinstance", "getattr",
                "print", "enumerate", "zip", "float", "int", "str",
                "bool", "min", "max", "abs", "sum", "list", "tuple",
                "dict", "set", "sorted", "repr", "hasattr", "setattr",
                "type", "id", "format", "round", "divmod", "all", "any"):
            return node  # hot builtins: no wrap needed
        self.wrapped_calls += 1
        node.func = ast.copy_location(
            ast.Call(func=_load(f"{_H}_call"), args=[node.func],
                     keywords=[]), node.func)
        return node

    # -- expressions --------------------------------------------------------

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        conv = (f"{_H}_and" if isinstance(node.op, ast.And) else f"{_H}_or")
        # fold left-to-right, each RHS deferred in a lambda (short-circuit)
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=_load(conv),
                args=[expr, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                                       kwonlyargs=[], kw_defaults=[],
                                       kwarg=None, defaults=[]),
                    body=rhs)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                ast.Call(func=_load(f"{_H}_not"), args=[node.operand],
                         keywords=[]), node)
        return node

    # -- nested scopes are not transformed ----------------------------------

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    # -- statements ---------------------------------------------------------

    def visit_If(self, node):
        self.generic_visit(node)
        # ALL-PATHS-RETURN form (produced by _normalize_tail_returns or
        # written directly): both branches end the function, so the `if`
        # is a pure choice of return value — emit nullary branch
        # closures over the current locals and dispatch through
        # convert_ifelse_return (concrete → plain call, traced →
        # lax.cond over the return values).
        if (_ends_in_return(node.body) and node.orelse
                and _ends_in_return(node.orelse)
                and not _has_loop_exit_or_yield(node.body)
                and not _has_loop_exit_or_yield(node.orelse)
                and not _has_walrus(node.test)):
            self.counter += 1
            i = self.counter
            t_name, f_name = f"{_H}_rett_{i}", f"{_H}_retf_{i}"
            empty = ast.arguments(posonlyargs=[], args=[], vararg=None,
                                  kwonlyargs=[], kw_defaults=[],
                                  kwarg=None, defaults=[])
            defs = [ast.FunctionDef(name=t_name, args=empty,
                                    body=node.body, decorator_list=[],
                                    returns=None, type_params=[]),
                    ast.FunctionDef(name=f_name, args=empty,
                                    body=node.orelse, decorator_list=[],
                                    returns=None, type_params=[])]
            ret = ast.Return(value=ast.Call(
                func=_load(f"{_H}_ifret"),
                args=[node.test, _load(t_name), _load(f_name)],
                keywords=[]))
            out = defs + [ret]
            for n in out:
                ast.copy_location(n, node)
                ast.fix_missing_locations(n)
            return out
        if _has_early_exit(node.body) or _has_early_exit(node.orelse):
            return node
        if _defines_scope(node.body + node.orelse):
            return node
        if _has_walrus(node.test):
            # a := in the test binds a name the nested test_fn would hide
            return node
        names = _assigned(node.body + node.orelse)
        if not names:
            # pure side-effect branches (e.g. list.append) — cannot be
            # expressed as a value-flow cond; leave to plain Python
            return node
        self.counter += 1
        i = self.counter
        t_name, f_name = f"{_H}_true_{i}", f"{_H}_false_{i}"
        # current values flow IN as parameters: a branch that reads a name
        # it also assigns would otherwise hit UnboundLocalError (the name
        # becomes branch-local), and an empty branch returns the incoming
        # value unchanged
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n, annotation=None) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=_names_tuple(names, ast.Load))

        def mk(fname, body):
            return ast.FunctionDef(
                name=fname, args=params,
                body=(body or [ast.Pass()]) + [ret],
                decorator_list=[], returns=None, type_params=[])

        in_true = set(_assigned(node.body))
        in_false = set(_assigned(node.orelse))

        def mask(which):
            return ast.Tuple(elts=[ast.Constant(value=n in which)
                                   for n in names], ctx=ast.Load())

        call = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(func=_load(f"{_H}_ifelse"),
                           args=[node.test, _load(t_name), _load(f_name),
                                 _names_tuple(names, ast.Load),
                                 _str_list(names),
                                 mask(in_true), mask(in_false)],
                           keywords=[]))
        out = (_prebind(names) +
               [mk(t_name, node.body), mk(f_name, node.orelse), call])
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_early_exit(node.body) or node.orelse:
            return node
        if _defines_scope(node.body):
            return node
        if _has_walrus(node.test):
            return node
        names = _assigned(node.body)
        if not names:
            return node
        observed = (set(_expr_loads(node.test))
                    | _read_before_write(node.body))
        needs_init = ast.Tuple(
            elts=[ast.Constant(value=n in observed) for n in names],
            ctx=ast.Load())
        self.counter += 1
        i = self.counter
        t_name, b_name = f"{_H}_test_{i}", f"{_H}_body_{i}"
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n, annotation=None) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        test_fn = ast.FunctionDef(
            name=t_name, args=params,
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        body_fn = ast.FunctionDef(
            name=b_name, args=params,
            body=node.body + [ast.Return(value=_names_tuple(names,
                                                            ast.Load))],
            decorator_list=[], returns=None, type_params=[])
        call = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(func=_load(f"{_H}_while"),
                           args=[_load(t_name), _load(b_name),
                                 _names_tuple(names, ast.Load),
                                 _str_list(names), needs_init],
                           keywords=[]))
        out = _prebind(names) + [test_fn, body_fn, call]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def visit_For(self, node):
        """``for <name> in range(...)`` → an equivalent while, which then
        converts via visit_While. Other iterables stay plain Python."""
        if (not isinstance(node.target, ast.Name)
                or node.orelse
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3
                or any(isinstance(a, ast.Starred) for a in node.iter.args)
                or _has_early_exit(node.body)):
            self.generic_visit(node)
            return node
        a = node.iter.args
        start = a[0] if len(a) > 1 else ast.Constant(value=0)
        stop = a[0] if len(a) == 1 else a[1]
        step = a[2] if len(a) > 2 else ast.Constant(value=1)
        self.counter += 1
        i_var = node.target.id
        # the running counter is an internal temp; the USER's loop variable
        # is assigned at the top of each iteration, so after the loop it
        # holds the last executed value (Python semantics: not one-past),
        # and stays unbound when the range is empty
        it_var = f"{_H}_it_{self.counter}"
        stop_var = f"{_H}_stop_{self.counter}"
        step_var = f"{_H}_step_{self.counter}"
        init = [
            ast.Assign(targets=[_store(it_var)], value=start),
            ast.Assign(targets=[_store(stop_var)], value=stop),
            ast.Assign(targets=[_store(step_var)], value=step),
            ast.Assign(targets=[_store(i_var)],
                       value=ast.Call(func=_load(f"{_H}_for_seed"),
                                      args=[_load(it_var), _load(stop_var),
                                            _load(step_var),
                                            ast.Constant(value=i_var)],
                                      keywords=[])),
        ]
        test = ast.Call(func=_load(f"{_H}_range_test"),
                        args=[_load(it_var), _load(stop_var),
                              _load(step_var)],
                        keywords=[])
        enter = ast.Assign(targets=[_store(i_var)], value=_load(it_var))
        bump = ast.AugAssign(target=_store(it_var), op=ast.Add(),
                             value=_load(step_var))
        loop = ast.While(test=test, body=[enter] + node.body + [bump],
                         orelse=[])
        out = []
        for s in init:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
            out.append(s)
        ast.copy_location(loop, node)
        ast.fix_missing_locations(loop)
        converted = self.visit_While(loop)
        out.extend(converted if isinstance(converted, list) else [converted])
        return out


_HELPERS = {
    f"{_H}_ifret": convert_ifelse_return,
    f"{_H}_ifelse": convert_ifelse,
    f"{_H}_while": convert_while,
    f"{_H}_and": convert_logical_and,
    f"{_H}_or": convert_logical_or,
    f"{_H}_not": convert_logical_not,
    f"{_H}_range_test": range_test,
    f"{_H}_for_seed": for_seed,
    f"{_H}_undef": _Undef,
    f"{_H}_call": convert_call,
}


def _uses_scope_stmts(tree) -> bool:
    return any(isinstance(n, (ast.Global, ast.Nonlocal))
               for n in ast.walk(tree))


def convert_control_flow(fn: Callable) -> Callable:
    """Rewrite fn's tensor-dependent control flow; on any obstacle return
    fn unchanged (to_static then behaves exactly as before, including its
    teaching error for traced conditions)."""
    if getattr(fn, "__not_to_static__", False):
        return fn
    if getattr(fn, "_p1t_dy2s_converted", False):
        return fn
    if inspect.ismethod(fn):
        # convert the underlying function, re-bind to the same instance
        import types
        conv = convert_control_flow(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    if "__class__" in fn.__code__.co_freevars:
        # zero-arg super() reads the implicit __class__ cell, which an
        # exec'd def outside the class body cannot have
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    import re as _re
    if _re.search(r"\.\s*__\w+[a-zA-Z0-9](?!_)", src) or \
            _re.search(r"\.\s*__\w+[a-zA-Z0-9]\b(?!__)", src):
        # private-name mangling (self.__attr) resolves against the class
        # the code was compiled in; recompiled outside it, the name stays
        # unmangled — bail rather than mis-resolve
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if _uses_scope_stmts(fdef):
        return fn

    transformer = _ControlFlowTransformer()
    fdef.decorator_list = []  # do not re-apply @to_static on exec
    # single-exit normalization first: early-return `if`s absorb the
    # rest of the function as their else-branch (semantics-preserving
    # for plain Python; enables the traced all-paths-return conversion)
    fdef.body = _normalize_tail_returns(fdef.body)
    new_body = []
    for stmt in fdef.body:
        res = transformer.visit(stmt)
        new_body.extend(res if isinstance(res, list) else [res])
    if transformer.counter == 0 and transformer.wrapped_calls == 0:
        return fn  # nothing converted — keep the original (zero risk)
    # recompile also when only CALLS were wrapped: the function itself
    # may be control-flow-free while its helpers are not
    fdef.body = new_body
    ast.fix_missing_locations(tree)

    if fn.__closure__:
        # closures force the snapshot namespace (free variables become
        # globals of the recompiled function; injecting them into the
        # REAL module globals could shadow module names)
        namespace = dict(fn.__globals__)
    else:
        # closure-free: compile against the LIVE module globals so later
        # rebinding of module-level helpers/config is seen (the helper
        # names are prefixed __p1t_dy2s_, collision-safe)
        namespace = fn.__globals__
    namespace.update(_HELPERS)
    if fn.__closure__:
        # snapshot free variables (cells) — the recompiled function reads
        # them as globals; late rebinding of the enclosing scope is out of
        # scope for the converter (documented)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                namespace[name] = cell.cell_contents
            except ValueError:
                return fn  # unresolved cell (self-reference) — bail out
    _missing = object()
    prev_binding = namespace.get(fdef.name, _missing)
    try:
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        exec(code, namespace)
    except Exception:
        return fn
    new_fn = namespace[fdef.name]
    # live-globals exec just bound the converted function over the
    # module's own name — restore the original so only to_static-reached
    # call sites see the conversion (no module-wide clobber)
    if prev_binding is _missing:
        del namespace[fdef.name]
    else:
        namespace[fdef.name] = prev_binding
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn._p1t_dy2s_converted = True
    return new_fn
