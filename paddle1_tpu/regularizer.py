"""Weight regularizers (reference python/paddle/fluid/regularizer.py:
L1Decay/L2Decay appended as grad-modifying ops; here applied in the
optimizer's update rule)."""

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param):
        return self.coeff * param


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param):
        import jax.numpy as jnp
        return self.coeff * jnp.sign(param)
