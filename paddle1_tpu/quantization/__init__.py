"""Quantization-aware training + post-training quantization.

Analog of the reference's slim quantization
(python/paddle/fluid/contrib/slim/quantization: QuantizationTransformPass
inserting fake_quantize_* / fake_dequantize_* ops, moving-average abs-max
observers). The TPU build quantizes at the LAYER level instead of graph
rewriting: ``QAT.quantize(model)`` swaps Conv2D/Linear for quantized
wrappers that fake-quant weights + activations with straight-through
gradients; ``PTQ`` calibrates ranges on sample data. int8 simulation runs
in bf16/f32 math (TPUs have no int8 MXU path in this generation; the value
is deploy-parity + smaller checkpoints)."""

from __future__ import annotations

import collections
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor
from ..nn.layer_base import Layer
from ..nn.layer_common import Linear
from ..nn.layer_conv_pool import Conv2D

__all__ = ["fake_quant", "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
           "QuantizedLinear", "QuantizedConv2D", "QAT", "PTQ",
           "QuantTensor", "quantize_weights_int8", "dequantize_weights",
           "Int8Linear", "quantize_decode"]


@jax.custom_vjp
def _ste_quant(x, scale, bits):
    qmax = 2.0 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax


def _ste_fwd(x, scale, bits):
    return _ste_quant(x, scale, bits), (x, scale)


def _ste_bwd(res, g):
    x, scale = res
    # straight-through: pass gradient where |x| <= scale, zero outside
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-8)).astype(g.dtype)
    return g * mask, None, None


_ste_quant.defvjp(_ste_fwd, _ste_bwd)


def fake_quant(x, scale, bits=8):
    """fake_quantize_dequantize with STE gradient (reference
    fake_quantize_op / fake_dequantize_op pair)."""
    t = x if isinstance(x, Tensor) else to_tensor(x)
    s = scale if isinstance(scale, Tensor) else to_tensor(
        np.asarray(scale, np.float32))
    return apply("fake_quant", lambda a, sc: _ste_quant(a, sc, bits),
                 (t, s))


class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max observer+quantizer (weights)."""

    def __init__(self, bits=8):
        super().__init__()
        self.bits = bits

    def forward(self, x):
        def f(a):
            scale = jnp.max(jnp.abs(a))
            return _ste_quant(a, scale, self.bits)
        return apply("fake_quant_abs_max", f, (x,))


class FakeQuantMovingAverageAbsMax(Layer):
    """EMA abs-max observer (activations) — reference
    moving_average_abs_max. Running scale is a buffer (state_dict'd)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale", to_tensor(np.zeros((), np.float32)))
        self.register_buffer("inited", to_tensor(np.zeros((), np.int32)))

    def forward(self, x):
        if self.training:
            cur = float(jnp.max(jnp.abs(x.data))) if not isinstance(
                x.data, jax.core.Tracer) else None
            if cur is not None:
                if int(self.inited.numpy()) == 0:
                    self.scale._data = jnp.asarray(cur, jnp.float32)
                    self.inited._data = jnp.asarray(1, jnp.int32)
                else:
                    self.scale._data = (self.momentum * self.scale.data +
                                        (1 - self.momentum) * cur)
        # No calibrated range yet (eval before any training forward, or a
        # jitted/functionalized forward where the EMA update above cannot
        # run): pass through rather than clamp everything to ~0. The guard
        # must be graph-safe — under jit ``inited`` is a tracer, and an
        # eager-only early return would silently quantize with scale=0,
        # collapsing every activation (ADVICE r1 finding).
        q = fake_quant(x, self.scale, self.bits)
        return apply("qat_inited_select",
                     lambda qa, xa, i: jnp.where(i > 0, qa, xa),
                     (q, x, self.inited))


class QuantizedLinear(Layer):
    def __init__(self, inner: Linear, bits=8):
        super().__init__()
        self.inner = inner
        self.w_quant = FakeQuantAbsMax(bits)
        self.a_quant = FakeQuantMovingAverageAbsMax(bits)

    def forward(self, x):
        from ..nn import functional as F
        xq = self.a_quant(x)
        wq = self.w_quant(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(Layer):
    def __init__(self, inner: Conv2D, bits=8):
        super().__init__()
        self.inner = inner
        self.w_quant = FakeQuantAbsMax(bits)
        self.a_quant = FakeQuantMovingAverageAbsMax(bits)

    def forward(self, x):
        from ..nn import functional as F
        xq = self.a_quant(x)
        wq = self.w_quant(self.inner.weight)
        return F.conv2d(xq, wq, self.inner.bias, self.inner._stride,
                        self.inner._padding, self.inner._dilation,
                        self.inner._groups, self.inner._data_format)


def _swap_layers(model: Layer, bits: int) -> int:
    n = 0
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, Linear):
            model._sub_layers[name] = QuantizedLinear(child, bits)
            n += 1
        elif isinstance(child, Conv2D):
            model._sub_layers[name] = QuantizedConv2D(child, bits)
            n += 1
        else:
            n += _swap_layers(child, bits)
    return n


class QAT:
    """Quantization-aware training driver (reference ImperativeQuantAware).

    qat = QAT(); qat.quantize(model)  → train as usual; weights/activations
    see int8 rounding in forward, STE in backward."""

    def __init__(self, bits: int = 8, config=None):
        self.bits = bits

    def quantize(self, model: Layer) -> Layer:
        count = _swap_layers(model, self.bits)
        if count == 0:
            import warnings
            warnings.warn("QAT.quantize: no Linear/Conv2D layers found")
        return model

    def save_quantized_model(self, model: Layer, path, input_spec=None):
        from ..jit import save as jit_save
        model.eval()
        jit_save(model, path, input_spec=input_spec)


# ---------------------------------------------------------------------------
# int8 decode-weight quantization (ISSUE 16 — the serving analog of the
# reference's slim quantization_pass: REAL int8 storage, not fake-quant
# simulation). Decode is memory-bound — every step re-reads every weight
# — so halving (f32→int8: quartering) weight bytes directly buys decode
# tokens/s-per-HBM-byte. Math stays f32: weights dequantize per-channel
# right before the matmul (TPUs of this generation have no int8 MXU
# path), so the win is bandwidth + footprint, not FLOPs.

# q: int8 [in, out]; scale: f32 [out] — per-OUTPUT-channel abs-max, the
# axis the matmul reduces against, so quantization error never mixes
# across channels. A pytree node: rides functional-state dicts and
# jit.save artifacts unchanged.
QuantTensor = collections.namedtuple("QuantTensor", ["q", "scale"])


def _quantize_array(w) -> QuantTensor:
    w = jnp.asarray(w)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return QuantTensor(q, scale.astype(jnp.float32))


def _dequantize_array(qt: QuantTensor):
    return qt.q.astype(jnp.float32) * qt.scale[None, :]


def quantize_weights_int8(params: Dict[str, object],
                          skip=("embed",)) -> Dict[str, object]:
    """Per-channel int8 quantization over a functional-state dict:
    every 2-D float ``*.weight`` leaf (except names containing a
    ``skip`` fragment — embeddings index rows, where a shared
    per-column scale costs disproportionate accuracy) becomes a
    :class:`QuantTensor`. Biases, norms, and everything else pass
    through untouched. The result is what ``serve_gen_int8`` loads:
    the engine stores THIS dict and dequantizes inside the trace."""
    out: Dict[str, object] = {}
    for name, arr in params.items():
        a = getattr(arr, "data", arr)
        eligible = (name.endswith(".weight")
                    and not any(s in name for s in skip)
                    and getattr(a, "ndim", 0) == 2
                    and jnp.issubdtype(jnp.asarray(a).dtype,
                                       jnp.floating))
        out[name] = _quantize_array(a) if eligible else a
    return out


def dequantize_weights(params: Dict[str, object]) -> Dict[str, object]:
    """Inverse of :func:`quantize_weights_int8` at the array level:
    QuantTensor leaves → dense f32. Called INSIDE the decode trace
    (GenerationEngine._apply_model) so the stored params — and the jit
    arguments, and the HBM census's view — stay int8; XLA fuses the
    dequant into the consuming matmul."""
    return {k: (_dequantize_array(v) if isinstance(v, QuantTensor)
                else v)
            for k, v in params.items()}


class Int8Linear(Layer):
    """Linear holding per-channel int8 weight storage (buffers ``q`` /
    ``scale``), dequantizing on the fly in forward — the layer-level
    form of the artifact pass, so :func:`quantize_decode` produces a
    module that ``jit.save`` serializes like any other (int8 weight in
    the checkpoint, f32 math in the graph)."""

    def __init__(self, inner: Linear):
        super().__init__()
        w = inner.weight.data
        qt = _quantize_array(w)
        self.in_features = int(w.shape[0])
        self.out_features = int(w.shape[1])
        self.register_buffer("q", Tensor(qt.q, stop_gradient=True))
        self.register_buffer("scale", Tensor(qt.scale,
                                             stop_gradient=True))
        self.bias = inner.bias

    def forward(self, x):
        from ..nn import functional as F
        w = apply("int8_dequant",
                  lambda q, s: q.astype(jnp.float32) * s[None, :],
                  (self.q, self.scale))
        return F.linear(x, w, self.bias)


def quantize_decode(model: Layer, skip=("embed",)) -> Layer:
    """Swap every eligible Linear for :class:`Int8Linear` in place (the
    module-level artifact pass; ``GenerationEngine`` uses the
    functional-state form instead). Returns the model. Layers whose
    qualified name contains a ``skip`` fragment are left dense."""

    def walk(layer: Layer, prefix: str) -> int:
        n = 0
        for name, child in list(layer._sub_layers.items()):
            qual = f"{prefix}.{name}" if prefix else name
            if isinstance(child, Linear) and not any(
                    s in qual for s in skip):
                layer._sub_layers[name] = Int8Linear(child)
                n += 1
            else:
                n += walk(child, qual)
        return n

    if walk(model, "") == 0:
        import warnings
        warnings.warn("quantize_decode: no Linear layers found")
    return model


class PTQ:
    """Post-training quantization: run calibration batches through the
    quantized model in eval-observer mode, freezing activation ranges
    (reference PostTrainingQuantization)."""

    def __init__(self, bits: int = 8):
        self.bits = bits

    def quantize(self, model: Layer, calib_loader, num_batches: int = 8
                 ) -> Layer:
        QAT(self.bits).quantize(model)
        model.train()        # observers update in train mode
        import itertools
        for batch in itertools.islice(iter(calib_loader), num_batches):
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            model(x)
        model.eval()
        return model
