"""Eager op surface: creation / manipulation / casting ops.

Analog of the reference's tensor-manipulation operators
(/root/reference/paddle/fluid/operators/{reshape_op.cc,transpose_op.cc,
concat_op.cc,split_op.cc,gather_op.cc,scatter_op.cc,...}) and
python/paddle/tensor/{creation.py,manipulation.py}.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core import dtype as dtypes
from ..core.generator import next_key
from ..core.tensor import Tensor, to_tensor
from ..core.errors import InvalidArgumentError

__all__ = []  # populated at bottom


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().reshape(-1)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return out


# -- creation -----------------------------------------------------------------

def zeros(shape, dtype=None, name=None):
    return to_tensor(jnp.zeros(_shape_list(shape),
                               dtypes.convert_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return to_tensor(jnp.ones(_shape_list(shape), dtypes.convert_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return to_tensor(jnp.full(_shape_list(shape), fill_value,
                              dtypes.convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return to_tensor(jnp.zeros_like(_t(x).data,
                                    dtype=dtypes.convert_dtype(dtype) if dtype else None))


def ones_like(x, dtype=None, name=None):
    return to_tensor(jnp.ones_like(_t(x).data,
                                   dtype=dtypes.convert_dtype(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None, name=None):
    return to_tensor(jnp.full_like(_t(x).data, fill_value,
                                   dtype=dtypes.convert_dtype(dtype) if dtype else None))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if builtins_all_int(start, end, step)
                 else dtypes.get_default_dtype())
    return to_tensor(jnp.arange(start, end, step, dtypes.convert_dtype(dtype)))


def builtins_all_int(*vals):
    return all(isinstance(v, (int, np.integer)) for v in vals)


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return to_tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                                  dtype=dtypes.convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return to_tensor(jnp.logspace(start, stop, int(num), base=base,
                                  dtype=dtypes.convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return to_tensor(jnp.eye(num_rows, num_columns,
                             dtype=dtypes.convert_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def f(x):
        if x.ndim == 1:
            out = jnp.diag(x, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset,
                               dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(x, offset=offset)
    return apply("diag", f, (_t(x),))


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda x: jnp.diagflat(x, k=offset), (_t(x),))


def meshgrid(*args, **kwargs):
    ts = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = apply("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                 tuple(_t(x) for x in ts), n_outputs=len(ts))
    return list(outs) if isinstance(outs, tuple) else [outs]


def tril(x, diagonal=0, name=None):
    return apply("tril", lambda x: jnp.tril(x, k=diagonal), (_t(x),))


def triu(x, diagonal=0, name=None):
    return apply("triu", lambda x: jnp.triu(x, k=diagonal), (_t(x),))


def clone(x, name=None):
    return x.clone()


def assign(x, output=None):
    val = _t(x)
    out = apply("assign", lambda x: x + jnp.zeros((), x.dtype), (val,))
    if output is not None:
        output._replace_impl(out)
        return output
    return out


# -- random creation ----------------------------------------------------------

def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype)
    return to_tensor(jax.random.normal(next_key(), _shape_list(shape), dt))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    """Gaussian creation (reference tensor/random.py gaussian)."""
    dt = dtypes.convert_dtype(dtype)
    return to_tensor(mean + std * jax.random.normal(
        next_key(), _shape_list(shape), dt))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = _t(mean) if isinstance(mean, Tensor) else mean
        s = _t(std) if isinstance(std, Tensor) else std
        base_shape = (m.shape if isinstance(m, Tensor) else s.shape)
        noise = jax.random.normal(next_key(), tuple(base_shape),
                                  dtypes.get_default_dtype())
        m_ = m.data if isinstance(m, Tensor) else m
        s_ = s.data if isinstance(s, Tensor) else s
        return to_tensor(m_ + s_ * noise)
    dt = dtypes.get_default_dtype()
    return to_tensor(mean + std * jax.random.normal(
        next_key(), _shape_list(shape if shape is not None else [1]), dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = dtypes.convert_dtype(dtype)
    key = jax.random.fold_in(jax.random.key(seed), 0) if seed else next_key()
    return to_tensor(jax.random.uniform(key, _shape_list(shape), dt,
                                        minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return to_tensor(jax.random.randint(next_key(), _shape_list(shape),
                                        low, high,
                                        dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = _t(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return to_tensor(jax.random.permutation(next_key(), n)
                     .astype(dtypes.convert_dtype(dtype)))


def bernoulli(x, name=None):
    x = _t(x)
    return to_tensor(jax.random.bernoulli(next_key(), x.data)
                     .astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = _t(x)
    logits = jnp.log(jnp.clip(x.data, 1e-30, None))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(*x.data.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for without-replacement sampling.
        g = jax.random.gumbel(next_key(), x.data.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return to_tensor(out.astype(jnp.int64))


# -- manipulation -------------------------------------------------------------

def cast(x, dtype):
    dt = dtypes.convert_dtype(dtype)
    return apply("cast", lambda x: x.astype(dt), (_t(x),))


def reshape(x, shape, name=None):
    s = _shape_list(shape)
    return apply("reshape", lambda x: jnp.reshape(x, s), (_t(x),))


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._replace_impl(out)
    return x


def transpose(x, perm, name=None):
    return apply("transpose", lambda x: jnp.transpose(x, perm), (_t(x),))


def t(x, name=None):
    x = _t(x)
    if x.ndim < 2:
        return x
    return apply("t", lambda x: jnp.swapaxes(x, -1, -2), (x,))


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis",
                 lambda x: jnp.moveaxis(x, source, destination), (_t(x),))


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda x: jnp.swapaxes(x, axis0, axis1), (_t(x),))


transpose_ = transpose


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _t(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0

    def f(x):
        shape = x.shape
        new = shape[:sa] + (-1,) + shape[ea + 1:]
        return jnp.reshape(x, new)
    return apply("flatten", f, (x,))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    if isinstance(x, Tensor):
        x._replace_impl(out)
        return x
    return out


def squeeze(x, axis=None, name=None):
    ax = None
    if axis is not None:
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        x_ = _t(x)
        ax = tuple(a for a in ax if x_.shape[a % x_.ndim] == 1)
    return apply("squeeze", lambda x: jnp.squeeze(x, axis=ax), (_t(x),))


def unsqueeze(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply("unsqueeze", lambda x: jnp.expand_dims(x, ax), (_t(x),))


squeeze_ = squeeze
unsqueeze_ = unsqueeze


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply("concat", lambda *xs: jnp.concatenate(xs, axis=axis),
                 tuple(_t(e) for e in x))


def stack(x, axis=0, name=None):
    return apply("stack", lambda *xs: jnp.stack(xs, axis=axis),
                 tuple(_t(e) for e in x))


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = dim - builtins_sum(s for s in sizes if s >= 0)
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def f(x):
        return tuple(jax.lax.slice_in_dim(x, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    outs = apply("split", f, (x,), n_outputs=len(sizes))
    return list(outs) if isinstance(outs, tuple) else [outs]


def unstack(x, axis=0, num=None, name=None):
    """Unpack along ``axis`` into a list (reference paddle.unstack)."""
    x = _t(x)
    n = num if num is not None else x.shape[axis]

    def f(x):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(x, n, axis=axis))
    outs = apply("unstack", f, (x,), n_outputs=n)
    return list(outs) if isinstance(outs, tuple) else [outs]


def builtins_sum(it):
    tot = 0
    for v in it:
        tot += v
    return tot


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(input, axis=0):
    x = _t(input)
    n = x.shape[axis]

    def f(x):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(x, n, axis=axis))
    outs = apply("unbind", f, (x,), n_outputs=n)
    return list(outs) if isinstance(outs, tuple) else [outs]


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _shape_list(repeat_times)
    return apply("tile", lambda x: jnp.tile(x, reps), (_t(x),))


def expand(x, shape, name=None):
    s = _shape_list(shape)
    x = _t(x)

    def f(x):
        target = list(s)
        # -1 entries keep original size (paddle semantics)
        offset = len(target) - x.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = x.shape[i - offset]
        return jnp.broadcast_to(x, target)
    return apply("expand", f, (x,))


def expand_as(x, y, name=None):
    return expand(x, _t(y).shape)


def broadcast_to(x, shape, name=None):
    s = _shape_list(shape)
    return apply("broadcast_to", lambda x: jnp.broadcast_to(x, s), (_t(x),))


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(_t(i).shape) for i in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [broadcast_to(i, list(out_shape)) for i in inputs]


def flip(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda x: jnp.flip(x, axis=ax), (_t(x),))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda x: jnp.rot90(x, k=k, axes=tuple(axes)),
                 (_t(x),))


def roll(x, shifts, axis=None, name=None):
    return apply("roll", lambda x: jnp.roll(x, shifts, axis=axis), (_t(x),))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def f(x, i):
        return jnp.take(x, i.reshape(-1) if i.ndim > 1 else i, axis=axis)
    return apply("gather", f, (_t(x), _t(index)))


def gather_nd(x, index, name=None):
    def f(x, idx):
        k = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return x[flat_idx]
    return apply("gather_nd", f, (_t(x), _t(index)))


def take_along_axis(arr, indices, axis, broadcast=True):
    return apply("take_along_axis",
                 lambda x, i: jnp.take_along_axis(x, i, axis=axis),
                 (_t(arr), _t(indices)))


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    def f(x, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(x.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(x, i, v, axis=axis, inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply"}[reduce]
        dn = jax.lax.ScatterDimensionNumbers  # fall back to take/segment ops
        # jnp lacks reduce modes for put_along_axis; emulate with at[] scatter.
        idx = [jnp.arange(s).reshape([-1 if d == k else 1
                                      for k in range(x.ndim)])
               for d, s in enumerate(i.shape)]
        idx[axis] = i
        if mode == "add":
            return x.at[tuple(idx)].add(v)
        return x.at[tuple(idx)].multiply(v)
    return apply("put_along_axis", f, (_t(arr), _t(indices), _t(values)))


def scatter(x, index, updates, overwrite=True, name=None):
    def f(x, i, u):
        if overwrite:
            return x.at[i].set(u)
        # paddle semantics for overwrite=False: zero the rows then add
        z = x.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)
    return apply("scatter", f, (_t(x), _t(index), _t(updates)))


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._replace_impl(out)
    return x


def scatter_nd_add(x, index, updates, name=None):
    def f(x, idx, u):
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return x.at[flat_idx].add(u)
    return apply("scatter_nd_add", f, (_t(x), _t(index), _t(updates)))


def scatter_nd(index, updates, shape, name=None):
    u = _t(updates)
    return scatter_nd_add(zeros(shape, u.dtype), index, updates)


def index_add(x, index, axis, value, name=None):
    def f(x, i, v):
        xm = jnp.moveaxis(x, axis, 0)
        out = xm.at[i].add(jnp.moveaxis(v, axis, 0))
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", f, (_t(x), _t(index), _t(value)))


def index_put(x, indices, value, accumulate=False, name=None):
    def f(x, v, *idx):
        if accumulate:
            return x.at[tuple(idx)].add(v)
        return x.at[tuple(idx)].set(v)
    return apply("index_put", f,
                 (_t(x), _t(value), *[_t(i) for i in indices]))


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return apply("masked_fill",
                     lambda x, m, v: jnp.where(m, v.astype(x.dtype), x),
                     (_t(x), _t(mask), value))
    return apply("masked_fill", lambda x, m: jnp.where(m, value, x),
                 (_t(x), _t(mask)))


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    def f(x):
        n = builtins_min(x.shape[-2], x.shape[-1])
        i = jnp.arange(n)
        return x.at[..., i, i].set(value)
    return apply("fill_diagonal", f, (_t(x),))


def builtins_min(a, b):
    return a if a < b else b


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = repeats.numpy()
        arr = _t(x).numpy()
        return to_tensor(np.repeat(arr, reps, axis=axis))
    return apply("repeat_interleave",
                 lambda x: jnp.repeat(x, repeats, axis=axis), (_t(x),))


def slice(input, axes, starts, ends):
    def _v(vs):
        return [int(v.item()) if isinstance(v, Tensor) else int(v) for v in vs]
    axes, starts, ends = list(axes), _v(starts), _v(ends)
    x = _t(input)

    def f(x):
        idx = [builtins_slice(None)] * x.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(s, e)
        return x[tuple(idx)]
    return apply("slice", f, (x,))


import builtins as _builtins  # noqa: E402
builtins_slice = _builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = _t(x)

    def f(x):
        idx = [builtins_slice(None)] * x.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(s, e, st)
        return x[tuple(idx)]
    return apply("strided_slice", f, (x,))


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    shape = _shape_list(shape)
    offsets = _shape_list(offsets) if offsets is not None else [0] * x.ndim

    def f(x):
        sizes = [sh if sh != -1 else x.shape[d] - off
                 for d, (sh, off) in enumerate(zip(shape, offsets))]
        return jax.lax.dynamic_slice(x, offsets, sizes)
    return apply("crop", f, (x,))


def numel(x, name=None):
    return to_tensor(int(np.prod(_t(x).shape)) if _t(x).ndim else 1)


def shape(input):
    return to_tensor(np.asarray(_t(input).shape, dtype=np.int32))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards

    def f(x):
        shard = x // size
        local = jnp.where(shard == shard_id, x % size, ignore_value)
        return local
    return apply("shard_index", f, (_t(input),))


def as_complex(x, name=None):
    return apply("as_complex",
                 lambda x: jax.lax.complex(x[..., 0], x[..., 1]), (_t(x),))


def as_real(x, name=None):
    return apply("as_real",
                 lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1),
                 (_t(x),))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply("view", lambda x: x.view(dtypes.convert_dtype(shape_or_dtype)),
                 (_t(x),))


def atleast_1d(*inputs):
    outs = [apply("atleast_1d", jnp.atleast_1d, (_t(x),)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [apply("atleast_2d", jnp.atleast_2d, (_t(x),)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [apply("atleast_3d", jnp.atleast_3d, (_t(x),)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


__all__ = sorted(
    k for k, v in list(globals().items())
    if callable(v) and not k.startswith("_") and
    getattr(v, "__module__", "") == __name__ and
    not k.startswith("builtins"))
