"""Fused Adam/AdamW update Pallas kernel.

TPU-native analog of the reference's fused optimizer CUDA kernels
(/root/reference/paddle/fluid/operators/optimizers/adam_op.cu — one kernel
reads p/g/m1/m2 and writes p/m1/m2): a single VMEM pass per block instead
of separate moment/param updates. The math is bit-identical to
optimizer.AdamW._update (decoupled decay; decay=0 + pre-adjusted grad
reproduces plain Adam).

Scalars (lr, bias corrections, decay) ride scalar-prefetch SMEM so `step`
stays a traced value. Runs in interpreter mode off-TPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adam_update", "supported"]

_COLS = 1024
_ROWS = 8
_CHUNK = _COLS * _ROWS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(n_elements: int) -> bool:
    # Tiny tensors (biases, norms) gain nothing; XLA fuses those fine.
    return n_elements >= _CHUNK


def _adam_kernel(s_ref, p_ref, g_ref, m1_ref, m2_ref,
                 po_ref, m1o_ref, m2o_ref, *, beta1, beta2, eps):
    lr, bc1, bc2, decay = s_ref[0], s_ref[1], s_ref[2], s_ref[3]
    g = g_ref[:].astype(jnp.float32)
    m1 = beta1 * m1_ref[:] + (1.0 - beta1) * g
    m2 = beta2 * m2_ref[:] + (1.0 - beta2) * g * g
    update = (m1 / bc1) / (jnp.sqrt(m2 / bc2) + eps)
    pf = p_ref[:].astype(jnp.float32) * (1.0 - lr * decay) - lr * update
    po_ref[:] = pf.astype(po_ref.dtype)
    m1o_ref[:] = m1
    m2o_ref[:] = m2


def fused_adam_update(p, g, m1, m2, lr, step, beta1, beta2, eps, decay):
    """One fused pass: returns (new_p, new_m1, new_m2).

    p: any shape/dtype; g same shape; m1/m2 f32. lr/step traced scalars;
    beta1/beta2/eps/decay python floats (decay may be traced).
    """
    shape, dtype = p.shape, p.dtype
    n = p.size
    pad = (-n) % _CHUNK
    rows = (n + pad) // _COLS

    def to2d(a, dt):
        flat = a.reshape(-1).astype(dt)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(rows, _COLS)

    p2 = to2d(p, dtype)
    g2 = to2d(g, dtype)
    m12 = to2d(m1, jnp.float32)
    m22 = to2d(m2, jnp.float32)

    stepf = step.astype(jnp.float32) if hasattr(step, "astype") \
        else jnp.float32(step)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        1.0 - beta1 ** stepf,
        1.0 - beta2 ** stepf,
        jnp.asarray(decay, jnp.float32),
    ])

    kernel = functools.partial(_adam_kernel, beta1=float(beta1),
                               beta2=float(beta2), eps=float(eps))
    # index maps under scalar-prefetch receive (grid_idx, scalar_ref)
    spec = pl.BlockSpec((_ROWS, _COLS), lambda i, s: (i, 0))
    new_p, new_m1, new_m2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows // _ROWS,),
            in_specs=[spec, spec, spec, spec],
            out_specs=[spec, spec, spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((rows, _COLS), dtype),
            jax.ShapeDtypeStruct((rows, _COLS), jnp.float32),
            jax.ShapeDtypeStruct((rows, _COLS), jnp.float32),
        ],
        interpret=_interpret(),
    )(scalars, p2, g2, m12, m22)

    unflat = lambda a, dt: a.reshape(-1)[:n].reshape(shape).astype(dt)
    return (unflat(new_p, dtype), unflat(new_m1, jnp.float32),
            unflat(new_m2, jnp.float32))
