"""Pallas flash-attention BACKWARD kernels (FlashAttention-2 split).

The forward (flash_attention.py) recomputes probabilities in XLA for the
backward; these kernels do the recompute in VMEM instead — logits and
probabilities never touch HBM in either pass:

* ``_dkv_kernel``: grid over (batch·head, k-block); one pass over the
  q-blocks accumulates dK and dV for the resident k-block.
* ``_dq_kernel``: grid over (batch·head, q-block); one pass over the
  k-blocks accumulates dQ for the resident q-block.

Both consume the forward's LSE and ``delta = rowsum(dout * out)``
(computed in XLA — one cheap fused reduction). Scalar-per-row inputs
ride a trailing singleton dim ([bh, n, 1]) which satisfies Mosaic's
(8, 128)-or-equal tiling rule without lane broadcasting.

Gated by core flag ``flash_backward`` — default ``auto`` (engaged on
TPU) since tools/tpu_kernel_smoke.py validated the Mosaic lowering on a
real chip (r5, TPU v5 lite: every dq/dk/dv variant bit-exact vs the XLA
recompute backward — chip_results/kernel_smoke.txt). ``never`` restores
the XLA recompute backward; interpret mode (``always`` off-TPU) does not
enforce the tiling rules (the forward's LSE layout bug only surfaced on
hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import BLOCK_K, BLOCK_Q, _NEG_INF, _interpret

__all__ = ["flash_attention_bwd", "supported"]


def supported(q_shape, k_shape) -> bool:
    _, nq, _, d = q_shape
    _, nk, _, _ = k_shape
    if nq % BLOCK_Q or nk % BLOCK_K:
        return False
    if d % 8 or d > 256:
        return False
    # the dkv pass keeps FULL q+do rows resident; the dq pass keeps
    # full k+v. Measured scoped-VMEM cost (r5, on-chip compile report
    # at nq=nk=16384, d=64: 32.25 MiB vs the 16 MiB limit) is ~32
    # bytes per row-element — operands + accumulators + pipeline
    # double-buffering — so gate on that model with headroom. Shapes
    # rejected here take the chunked XLA recompute backward
    # (_bwd_xla), which is HBM-bounded instead.
    budget = 14 * 1024 * 1024
    if 32 * max(nq, nk) * d > budget:
        return False
    return True


def _masks(s_shape, q0, k0, nk, nq, causal, mask_ref):
    """Additive -inf mask for one [BQ, BK] logits tile."""
    add = None
    if causal:
        q_ids = (q0 + (nk - nq) +
                 jax.lax.broadcasted_iota(jnp.int32, s_shape, 0))
        k_ids = k0 + jax.lax.broadcasted_iota(jnp.int32, s_shape, 1)
        add = jnp.where(q_ids >= k_ids, 0.0, _NEG_INF)
    if mask_ref is not None:
        mk = mask_ref[0, pl.ds(k0, s_shape[1]), 0]        # [BK]
        pad = jnp.where(mk[None, :] > 0.5, 0.0, _NEG_INF)
        add = pad if add is None else add + pad
    return add


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, mask_ref=None):
    # k_ref/v_ref: [BLOCK_K, D] (resident); q/do: [N_q, D] full rows;
    # lse/delta: [N_q, 1]
    k_blk = pl.program_id(1)
    nq = q_ref.shape[0]
    nk = pl.num_programs(1) * BLOCK_K
    d = q_ref.shape[1]
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * BLOCK_Q, BLOCK_Q), :].astype(jnp.float32)
        do = do_ref[pl.ds(i * BLOCK_Q, BLOCK_Q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * BLOCK_Q, BLOCK_Q), 0]
        delta = delta_ref[pl.ds(i * BLOCK_Q, BLOCK_Q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        add = _masks(s.shape, i * BLOCK_Q, k_blk * BLOCK_K, nk, nq,
                     causal, mask_ref)
        if add is not None:
            s = s + add
        # lse is +inf for fully-masked rows (remapped by the wrapper):
        # p underflows to an exact 0 there
        p = jnp.exp(s - lse[:, None])                     # [BQ, BK]
        dv = dv + jax.lax.dot_general(p, do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((BLOCK_K, d), jnp.float32)
    dv0 = jnp.zeros((BLOCK_K, d), jnp.float32)
    if causal:
        # q-blocks strictly before this k-block see none of it
        lo = jnp.maximum(
            (k_blk * BLOCK_K - (nk - nq)) // BLOCK_Q, 0)
    else:
        lo = 0
    dk, dv = jax.lax.fori_loop(lo, nq // BLOCK_Q, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale, causal, mask_ref=None):
    # q/do: [BLOCK_Q, D] resident; k/v full; lse/delta: [BLOCK_Q, 1]
    q_blk = pl.program_id(1)
    nk = k_ref.shape[0]
    nq = pl.num_programs(1) * BLOCK_Q
    d = q_ref.shape[1]
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0]
    delta = delta_ref[:, 0]

    def body(i, dq):
        k = k_ref[pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        add = _masks(s.shape, q_blk * BLOCK_Q, i * BLOCK_K, nk, nq,
                     causal, mask_ref)
        if add is not None:
            s = s + add
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((BLOCK_Q, d), jnp.float32)
    if causal:
        hi = pl.cdiv((q_blk + 1) * BLOCK_Q + (nk - nq), BLOCK_K)
        hi = jnp.minimum(hi, nk // BLOCK_K)
    else:
        hi = nk // BLOCK_K
    dq = jax.lax.fori_loop(0, hi, body, dq0)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def flash_attention_bwd(q, k, v, out, lse, dout, scale, causal,
                        padding_mask=None):
    """(dq, dk, dv) in the paddle [B, N, H, D] layout — drop-in for
    flash_attention._bwd_xla."""
    b, nq, h, d = q.shape
    nk = k.shape[1]
    to_bhnd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, -1, d)
    qh, kh, vh = to_bhnd(q), to_bhnd(k), to_bhnd(v)
    doh, oh = to_bhnd(dout), to_bhnd(out)

    # delta = rowsum(dout * out): one fused XLA reduction
    delta = jnp.sum(doh.astype(jnp.float32) * oh.astype(jnp.float32),
                    axis=-1, keepdims=True)               # [bh, nq, 1]
    # fully-padded rows carry the forward's FINITE sentinel LSE; remap to
    # +inf so exp(s - lse) is an exact 0 for every key (same guard as
    # _bwd_xla — exp(s - (-1e30)) would be exp(0) = 1, garbage grads)
    lse3 = lse.reshape(b * h, nq, 1).astype(jnp.float32)
    lse3 = jnp.where(lse3 > _NEG_INF * 0.1, lse3, jnp.inf)

    args = [qh, kh, vh, doh, lse3, delta]
    qspec = pl.BlockSpec((None, BLOCK_Q, d), lambda bh, i: (bh, i, 0))
    kfull = pl.BlockSpec((None, nk, d), lambda bh, i: (bh, 0, 0))
    qfull = pl.BlockSpec((None, nq, d), lambda bh, i: (bh, 0, 0))
    kspec = pl.BlockSpec((None, BLOCK_K, d), lambda bh, i: (bh, i, 0))
    row_q = pl.BlockSpec((None, BLOCK_Q, 1), lambda bh, i: (bh, i, 0))
    row_qfull = pl.BlockSpec((None, nq, 1), lambda bh, i: (bh, 0, 0))

    mask_arg, mask_specs = (), ()
    if padding_mask is not None:
        mk = padding_mask.astype(jnp.float32).reshape(b, 1, nk, 1)
        mask_arg = (mk,)
        mask_specs = (pl.BlockSpec((None, 1, nk, 1),
                                   lambda bh, i: (bh // h, 0, 0, 0)),)

    def with_mask(kern, n_outs):
        if padding_mask is None:
            return functools.partial(kern, scale=scale, causal=causal)

        def k2(*refs):
            *ins, m_ref = refs[:len(refs) - n_outs]
            outs = refs[len(refs) - n_outs:]
            kern(*ins, *outs, scale=scale, causal=causal,
                 mask_ref=m_ref)
        return k2

    # dkv pass
    dk, dv = pl.pallas_call(
        with_mask(_dkv_kernel, 2),
        grid=(b * h, nk // BLOCK_K),
        in_specs=[qfull, kspec, kspec, qfull, row_qfull, row_qfull,
                  *mask_specs],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((b * h, nk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, nk, d), v.dtype)],
        interpret=_interpret(),
    )(*args, *mask_arg)

    # dq pass
    dq = pl.pallas_call(
        with_mask(_dq_kernel, 1),
        grid=(b * h, nq // BLOCK_Q),
        in_specs=[qspec, kfull, kfull, qspec, row_q, row_q, *mask_specs],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, nq, d), q.dtype),
        interpret=_interpret(),
    )(*args, *mask_arg)

    back = lambda x: x.reshape(b, h, -1, d).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv)
