"""Fused batch-norm BACKWARD Pallas kernels.

One kernel produces dx, dgamma, dbeta (and dresidual) — the analog of
the reference's FusedBatchNormActGradKernel: the activation mask, the
two per-channel reductions (sum dy, sum dy*xhat) and the dx recurrence
never leave the kernel, where the XLA lowering spends three
memory-bound passes plus layout copies per BN
(chip_results/resnet_trace_b32.txt).

Training-mode dx couples every row to the batch reductions, so the
kernel mirrors the forward's two-phase sequential grid: phase 0
accumulates the f32 reduction outputs in VMEM, phase 1 streams dx
(and dresidual). Eval-mode dx is row-local, so its kernel is a single
phase that accumulates dgamma/dbeta while it streams.

The ``fused_bn_bwd`` flag picks between these kernels and
``*_bwd_xla`` — the jnp composition that is both the CPU/unaligned
fallback and the on-chip ablation arm (the ``fused_adam`` lesson:
publish the ablation if XLA wins). Same bf16 discipline as the
forward: reductions in f32, count exact, outputs cast at the edge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import block_rows as _block_rows, interpret as _interpret
from .fused_bn import supported

__all__ = ["train_bwd", "norm_bwd", "train_bwd_xla", "norm_bwd_xla"]


def _pallas_bwd_active(shape, dtype) -> bool:
    from ...core.flags import flag_active
    return flag_active("fused_bn_bwd") and supported(shape, dtype)


def _masked_dy(dy_ref, y_ref, act):
    dy = dy_ref[:].astype(jnp.float32)
    if act == "relu":
        dy = dy * (y_ref[:] > 0).astype(jnp.float32)
    return dy


# ---------------------------------------------------------------------------
# Training-mode backward (batch stats): two-phase grid
# ---------------------------------------------------------------------------


def _train_bwd_kernel(*refs, eps, act, inv_count, with_res):
    if with_res:
        (x_ref, g_ref, m_ref, v_ref, y_ref, dy_ref,
         dx_ref, dg_ref, db_ref, dr_ref) = refs
    else:
        (x_ref, g_ref, m_ref, v_ref, y_ref, dy_ref,
         dx_ref, dg_ref, db_ref) = refs
        dr_ref = None
    p = pl.program_id(0)
    i = pl.program_id(1)
    dy = _masked_dy(dy_ref, y_ref, act)
    rstd = jax.lax.rsqrt(v_ref[:] + eps)
    xhat = (x_ref[:].astype(jnp.float32) - m_ref[:]) * rstd

    @pl.when(p == 0)
    def _accumulate():
        sg = jnp.sum(dy * xhat, axis=0, keepdims=True)
        sb = jnp.sum(dy, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _():
            dg_ref[:] = sg
            db_ref[:] = sb

        @pl.when(i != 0)
        def _():
            dg_ref[:] = dg_ref[:] + sg
            db_ref[:] = db_ref[:] + sb

    @pl.when(p == 1)
    def _stream():
        dx = g_ref[:].astype(jnp.float32) * rstd * (
            dy - db_ref[:] * inv_count - xhat * dg_ref[:] * inv_count)
        dx_ref[:] = dx.astype(dx_ref.dtype)
        if dr_ref is not None:
            dr_ref[:] = dy.astype(dr_ref.dtype)


def _train_bwd_pallas(x2, g, mean, var, y2, dy2, eps, act, with_res):
    rows, c = x2.shape
    br = _block_rows(rows, c)
    kernel = functools.partial(
        _train_bwd_kernel, eps=eps, act=act, inv_count=1.0 / rows,
        with_res=with_res)
    row_spec = pl.BlockSpec((br, c), lambda p, i: (i, 0))
    park_spec = pl.BlockSpec((br, c), lambda p, i: (p * i, 0))
    ch_spec = pl.BlockSpec((1, c), lambda p, i: (0, 0))
    out_specs = [park_spec, ch_spec, ch_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, c), x2.dtype),
                 jax.ShapeDtypeStruct((1, c), jnp.float32),
                 jax.ShapeDtypeStruct((1, c), jnp.float32)]
    if with_res:
        out_specs.append(park_spec)
        out_shape.append(jax.ShapeDtypeStruct((rows, c), dy2.dtype))
    outs = pl.pallas_call(
        kernel,
        grid=(2, rows // br),
        in_specs=[row_spec, ch_spec, ch_spec, ch_spec, row_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(x2, g.reshape(1, c), mean.astype(jnp.float32).reshape(1, c),
      var.astype(jnp.float32).reshape(1, c), y2, dy2)
    dx, dg, db = outs[0], outs[1].reshape(c), outs[2].reshape(c)
    if with_res:
        return dx, dg, db, outs[3]
    return dx, dg, db


def train_bwd_xla(x2, g, mean, var, y2, dy2, eps, act, with_res=False):
    """jnp composition of the training-mode backward — the fallback and
    the on-chip ablation arm for the Pallas kernel."""
    n = x2.shape[0]
    dy = dy2.astype(jnp.float32)
    if act == "relu":
        dy = dy * (y2 > 0).astype(jnp.float32)
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    xhat = (x2.astype(jnp.float32) - mean[None, :]) * rstd[None, :]
    dg = jnp.sum(dy * xhat, axis=0)
    db = jnp.sum(dy, axis=0)
    dx = (g.astype(jnp.float32) * rstd)[None, :] * (
        dy - db[None, :] / n - xhat * dg[None, :] / n)
    dx = dx.astype(x2.dtype)
    if with_res:
        return dx, dg, db, dy.astype(dy2.dtype)
    return dx, dg, db


def train_bwd(x2, g, mean, var, y2, dy2, eps, act, with_res=False):
    """dx/dgamma/dbeta (+dresidual) for training-mode fused BN: the
    Pallas one-pass kernel when ``fused_bn_bwd`` resolves active, else
    the XLA composition."""
    if _pallas_bwd_active(x2.shape, x2.dtype):
        return _train_bwd_pallas(x2, g, mean, var, y2, dy2, float(eps),
                                 act, with_res)
    return train_bwd_xla(x2, g, mean, var, y2, dy2, float(eps), act,
                         with_res)


# ---------------------------------------------------------------------------
# Given-stats backward (eval / SyncBatchNorm normalize): single phase
# ---------------------------------------------------------------------------


def _norm_bwd_kernel(*refs, eps, act, with_res):
    if with_res:
        (x_ref, g_ref, m_ref, v_ref, y_ref, dy_ref,
         dx_ref, dg_ref, db_ref, dr_ref) = refs
    else:
        (x_ref, g_ref, m_ref, v_ref, y_ref, dy_ref,
         dx_ref, dg_ref, db_ref) = refs
        dr_ref = None
    i = pl.program_id(0)
    dy = _masked_dy(dy_ref, y_ref, act)
    rstd = jax.lax.rsqrt(v_ref[:] + eps)
    xhat = (x_ref[:].astype(jnp.float32) - m_ref[:]) * rstd
    sg = jnp.sum(dy * xhat, axis=0, keepdims=True)
    sb = jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dg_ref[:] = sg
        db_ref[:] = sb

    @pl.when(i != 0)
    def _():
        dg_ref[:] = dg_ref[:] + sg
        db_ref[:] = db_ref[:] + sb

    dx_ref[:] = (dy * g_ref[:].astype(jnp.float32) * rstd).astype(
        dx_ref.dtype)
    if dr_ref is not None:
        dr_ref[:] = dy.astype(dr_ref.dtype)


def _norm_bwd_pallas(x2, g, mean, var, y2, dy2, eps, act, with_res):
    rows, c = x2.shape
    br = _block_rows(rows, c)
    kernel = functools.partial(_norm_bwd_kernel, eps=eps, act=act,
                               with_res=with_res)
    row_spec = pl.BlockSpec((br, c), lambda i: (i, 0))
    ch_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    out_specs = [row_spec, ch_spec, ch_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, c), x2.dtype),
                 jax.ShapeDtypeStruct((1, c), jnp.float32),
                 jax.ShapeDtypeStruct((1, c), jnp.float32)]
    if with_res:
        out_specs.append(row_spec)
        out_shape.append(jax.ShapeDtypeStruct((rows, c), dy2.dtype))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[row_spec, ch_spec, ch_spec, ch_spec, row_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(x2, g.reshape(1, c), mean.astype(jnp.float32).reshape(1, c),
      var.astype(jnp.float32).reshape(1, c), y2, dy2)
    dx, dg, db = outs[0], outs[1].reshape(c), outs[2].reshape(c)
    if with_res:
        return dx, dg, db, outs[3]
    return dx, dg, db


def norm_bwd_xla(x2, g, mean, var, y2, dy2, eps, act, with_res=False):
    """jnp composition of the given-stats backward."""
    dy = dy2.astype(jnp.float32)
    if act == "relu":
        dy = dy * (y2 > 0).astype(jnp.float32)
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    xhat = (x2.astype(jnp.float32) - mean[None, :]) * rstd[None, :]
    dg = jnp.sum(dy * xhat, axis=0)
    db = jnp.sum(dy, axis=0)
    dx = (dy * (g.astype(jnp.float32) * rstd)[None, :]).astype(x2.dtype)
    if with_res:
        return dx, dg, db, dy.astype(dy2.dtype)
    return dx, dg, db


def norm_bwd(x2, g, mean, var, y2, dy2, eps, act, with_res=False):
    """dx/dgamma/dbeta (+dresidual) for given-stats fused BN."""
    if _pallas_bwd_active(x2.shape, x2.dtype):
        return _norm_bwd_pallas(x2, g, mean, var, y2, dy2, float(eps),
                                act, with_res)
    return norm_bwd_xla(x2, g, mean, var, y2, dy2, float(eps), act,
                        with_res)
