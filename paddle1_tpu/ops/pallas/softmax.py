"""Fused softmax Pallas kernel.

TPU-native analog of the reference's fused softmax CUDA kernels
(/root/reference/paddle/fluid/operators/softmax_cudnn_op.cu and the
fused-attention softmax inside operators/fused/): one VMEM pass per row
block computes max, exp, sum, and the normalized output — no HBM
round-trips for the intermediates (BASELINE.md config 3 names this
kernel family explicitly).

Forward = Pallas kernel; backward = the closed-form softmax vjp
(dx = p * (dy - sum(dy * p))), which XLA fuses tightly. Interpret mode
runs the same kernel path on CPU for tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import block_rows as _block_rows, interpret as _interpret

__all__ = ["fused_softmax", "supported"]


def supported(shape, axis: int) -> bool:
    """Last-axis softmax, lane-aligned non-empty rows tiling into VMEM."""
    nd = len(shape)
    if nd < 2 or axis not in (-1, nd - 1):
        return False
    h = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    if h <= 0 or h % 128:
        return False
    return _block_rows(rows, h) > 0


def _softmax_kernel(x_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)                  # [BR, H]
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=1, keepdims=True)).astype(y_ref.dtype)


def _softmax_fwd(x2):
    rows, h = x2.shape
    br = _block_rows(rows, h)
    return pl.pallas_call(
        _softmax_kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x2.dtype),
        interpret=_interpret(),
    )(x2)


@jax.custom_vjp
def _sm(x2):
    return _softmax_fwd(x2)


def _sm_vjp_fwd(x2):
    p = _softmax_fwd(x2)
    return p, p


def _sm_vjp_bwd(p, dy):
    pf = p.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    dx = pf * (dyf - jnp.sum(dyf * pf, axis=1, keepdims=True))
    return (dx.astype(p.dtype),)


_sm.defvjp(_sm_vjp_fwd, _sm_vjp_bwd)


def fused_softmax(x):
    """Softmax over the last axis. x: [..., H]."""
    h = x.shape[-1]
    return _sm(x.reshape(-1, h)).reshape(x.shape)
