"""Pallas TPU kernels (the analog of the reference's hand-fused CUDA kernels
in /root/reference/paddle/fluid/operators/fused/): flash attention, fused
layer_norm, fused softmax, fused adam, fused batch norm
(stats+normalize+activation+residual forward and one-pass dx/dgamma/dbeta
backward), ring attention.

Each kernel module exposes ``supported(...)`` gates so callers fall back to
plain XLA compositions on CPU/interpret mode or unaligned shapes.
"""
