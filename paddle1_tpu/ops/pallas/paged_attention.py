"""Paged-attention gather kernel (decode over the block-paged KV pool).

The paged decode cache (ISSUE 16) keeps K/V in per-layer global pools of
fixed-size pages — ``[pages, page_size, heads, dim]`` — with a
``[slots, max_pages_per_slot]`` int32 page table mapping each decode
slot's logical positions onto pool pages. Attention then needs a
*gather*: slot ``s``'s query window must read pages
``table[s, 0..ceil(len/page_size))``, scattered anywhere in the pool.

Two arms, same contract (used by nn.functional.paged_attention):

* :func:`paged_attention_ref` — XLA ``take`` composition. Materializes
  the gathered ``[slots, capacity, heads, dim]`` K/V, so it is the
  CPU/ablation arm and the numerics oracle.
* :func:`paged_attention` — the Pallas kernel. Scalar-prefetches the
  page table and per-slot base positions (PrefetchScalarGridSpec), so
  the BlockSpec index map itself chases ``table[s, j]``: each grid step
  DMAs exactly one page of K/V into VMEM and folds it into an
  online-softmax accumulator. The gathered cache never exists in HBM —
  the page table IS the gather.

Masking derives from position alone: query row ``i`` of slot ``s``
attends key positions ``<= base[s] + i`` (``base`` = the slot's length
before this window was written). Pages past the cursor — including the
reserved parking page that free slots' table rows point at — are fully
masked, so pool garbage never reaches the softmax of a live slot.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128  # Mosaic minor-dim tile (see flash_attention)
_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(q_shape, kp_shape) -> bool:
    """Tile-aligned shapes only; everything else uses the ref arm.
    ``q``: [slots, window, heads, dim]; ``kp``: [pages, page_size,
    heads, dim]."""
    if len(q_shape) != 4 or len(kp_shape) != 4:
        return False
    _, w, _, d = q_shape
    _, ps, _, _ = kp_shape
    if d % 8 or d > 256:
        return False
    if ps % 8:
        return False
    if w < 1 or w > 64:  # decode windows only (1 + spec_tokens)
        return False
    return True


def paged_attention_ref(q, kp, vp, table, base,
                        scale: Optional[float] = None):
    """XLA gather arm: materialize each slot's K/V via ``take`` over the
    page table, then masked softmax. q: [S, W, H, D]; kp/vp:
    [P, ps, H, D]; table: [S, mpps] int32; base: [S] int32 (slot length
    before this window). Returns [S, W, H, D]."""
    s_, w, h, d = q.shape
    ps = kp.shape[1]
    mpps = table.shape[1]
    cap = mpps * ps
    sc = float(scale) if scale is not None else float(1.0 / (d ** 0.5))
    flat = table.astype(jnp.int32).reshape(-1)
    k = jnp.take(kp, flat, axis=0).reshape(s_, cap, h, d)
    v = jnp.take(vp, flat, axis=0).reshape(s_, cap, h, d)
    logits = jnp.einsum("swhd,skhd->shwk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sc
    kpos = jnp.arange(cap, dtype=jnp.int32)
    qpos = base.astype(jnp.int32)[:, None] + jnp.arange(w, dtype=jnp.int32)
    mask = kpos[None, None, :] <= qpos[:, :, None]  # [S, W, cap]
    logits = jnp.where(mask[:, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("shwk,skhd->swhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _kernel(table_ref, base_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, scale, page_size):
    # grid (S, H, mpps); q_ref/o_ref: [W, D]; k_ref/v_ref: [ps, D] —
    # the page table already steered this block's DMA (index map), so
    # the kernel body only folds one page into the online softmax.
    s = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    w = q_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    q = q_ref[...].astype(jnp.float32) * scale
    k = k_ref[...].astype(jnp.float32)
    sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [W, ps]
    base = base_ref[s]
    kpos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (w, page_size), 1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (w, page_size), 0)
    sc = jnp.where(kpos <= base + rows, sc, _NEG_INF)

    m_prev = m_ref[...][:, :1]  # [W, 1]; lanes hold copies
    l_prev = l_ref[...][:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(sc - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v_ref[...].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = jax.lax.broadcast_in_dim(m_new[:, 0], m_ref.shape, (0,))
    l_ref[...] = jax.lax.broadcast_in_dim(l_new[:, 0], l_ref.shape, (0,))

    @pl.when(j == nj - 1)
    def _finalize():
        m = m_ref[...][:, :1]
        l = l_ref[...][:, :1]
        # a row with zero visible keys never happens for a live slot
        # (base >= 0 makes key 0 visible to every row), but free slots
        # ride the dispatch with parked tables — keep their output
        # finite instead of 0/0
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(m > _NEG_INF * 0.5, acc_ref[...] / l_safe, 0.0)
        o_ref[...] = out.astype(o_ref.dtype)


def paged_attention(q, kp, vp, table, base,
                    scale: Optional[float] = None):
    """Pallas gather arm, same contract as :func:`paged_attention_ref`.
    Grid (slots, heads, pages-per-slot); the scalar-prefetched table
    steers each step's K/V page DMA, scratch carries the online-softmax
    (m, l, acc) across the page axis."""
    s_, w, h, d = q.shape
    ps = kp.shape[1]
    mpps = table.shape[1]
    sc = float(scale) if scale is not None else float(1.0 / (d ** 0.5))
    kernel = functools.partial(_kernel, scale=sc, page_size=ps)
    # index maps under scalar-prefetch receive (*grid_idx, *scalar_refs)
    qspec = pl.BlockSpec((None, w, None, d),
                         lambda s, hh, j, t, b: (s, 0, hh, 0))
    pspec = pl.BlockSpec((None, ps, None, d),
                         lambda s, hh, j, t, b: (t[s, j], 0, hh, 0))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s_, h, mpps),
            in_specs=[qspec, pspec, pspec],
            out_specs=pl.BlockSpec((None, w, None, d),
                                   lambda s, hh, j, t, b: (s, 0, hh, 0)),
            scratch_shapes=[
                pltpu.VMEM((w, _LANES), jnp.float32),
                pltpu.VMEM((w, _LANES), jnp.float32),
                pltpu.VMEM((w, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s_, w, h, d), q.dtype),
        interpret=_interpret(),
    )(table.astype(jnp.int32), base.astype(jnp.int32), q, kp, vp)
    return out
