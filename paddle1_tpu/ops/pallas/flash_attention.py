"""Flash attention Pallas kernel (TPU MXU/VMEM-native fused attention).

Replaces the reference's fused multihead attention CUDA kernels
(/root/reference/paddle/fluid/operators/fused/ attention ops) with the
TPU idiom: online-softmax blocking in VMEM, one pass over K/V per query
block, logits never materialized in HBM.

Layout: [B, N, H, D] (paddle layout, matching nn.functional.attention).
Forward = Pallas kernel (+ log-sum-exp residual); backward = XLA
recompute from the LSE (flash-style, no stored probabilities).
Runs in interpreter mode off-TPU so tests exercise the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_Q = 128
BLOCK_K = 128
_LANES = 128  # Mosaic minor-dim tile: scalar-per-row outputs are stored
              # broadcast across one 128-lane register row
_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(q_shape, k_shape, causal: bool = False) -> bool:
    """Tile-aligned shapes only; everything else uses attention_ref."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    _, nq, _, d = q_shape
    _, nk, _, _ = k_shape
    if nq % BLOCK_Q or nk % BLOCK_K:
        return False
    if causal and nq > nk:
        # bottom-right causal leaves leading queries with ZERO visible
        # keys; the zero-sumexp sentinel would poison the vjp — let
        # attention_ref handle this degenerate alignment
        return False
    if d % 8 or d > 256:
        return False
    # K+V rows for one (batch, head) must fit in VMEM comfortably.
    # ">=": nk=16384/d=64 lands EXACTLY on the 8 MiB boundary and the
    # real scoped-vmem cost (16.12 MiB vs the 16 MiB limit, r5 on-chip
    # compile report) makes it a coin flip across compile contexts —
    # boundary shapes must not pass
    if 2 * nk * d * 4 >= 8 * 1024 * 1024:
        return False
    return True


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, mask_ref=None):
    # q_ref: [BLOCK_Q, D]; k_ref/v_ref: [N_k, D]; o_ref: [BLOCK_Q, D]
    # mask_ref (optional): [1, N_k] f32, 1.0 = attend / 0.0 = padding.
    q_blk = pl.program_id(1)
    nk = k_ref.shape[0]
    nq = pl.num_programs(1) * BLOCK_Q
    d = q_ref.shape[1]
    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, BK]
        if mask_ref is not None:
            mk = mask_ref[0, pl.ds(i * block_k, block_k)]  # [BK]
            s = jnp.where(mk[None, :] > 0.5, s, _NEG_INF)
        if causal:
            # bottom-right alignment (query i attends keys j <= i + nk-nq),
            # matching attention_ref's tril(..., nk - nq)
            q_ids = (q_blk * BLOCK_Q + (nk - nq) +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (BLOCK_Q, block_k), 0))
            k_ids = (i * block_k +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (BLOCK_Q, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((BLOCK_Q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((BLOCK_Q,), jnp.float32)
    acc0 = jnp.zeros((BLOCK_Q, d), jnp.float32)
    n_blocks = nk // block_k
    if causal:
        # blocks strictly above the (aligned) diagonal contribute nothing
        hi = (q_blk + 1) * BLOCK_Q + (nk - nq)
        n_blocks_eff = jnp.minimum(n_blocks, pl.cdiv(hi, block_k))
        m, l, acc = jax.lax.fori_loop(0, n_blocks_eff, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # Rows with zero visible keys (fully-padded batch entry): m is still the
    # sentinel and p degenerated to exp(0)=1 per key inside the loop. Gate
    # those rows to zero output and sentinel LSE so the backward (which
    # keys p off the LSE) produces exact zero gradients for them.
    visible = m > _NEG_INF * 0.5
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(visible[:, None], acc / l_safe[:, None], 0.0)
    o_ref[:] = out.astype(o_ref.dtype)
    # [BLOCK_Q] → [BLOCK_Q, _LANES]: Mosaic requires the last two block dims
    # tile to (8, 128), so the per-row LSE is broadcast across one lane row
    # (same layout as jax's own TPU flash kernel's l/m outputs)
    lse = jnp.where(visible, m + jnp.log(l_safe), _NEG_INF)
    lse_ref[:] = jax.lax.broadcast_in_dim(
        lse.astype(jnp.float32), (BLOCK_Q, _LANES), (0,))


def _flash_fwd(q, k, v, scale, causal, padding_mask=None):
    b, nq, h, d = q.shape
    nk = k.shape[1]
    # [B, N, H, D] → [B*H, N, D]
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, nq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, nk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, nk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=BLOCK_K)
    in_specs = [
        pl.BlockSpec((None, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((None, nk, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((None, nk, d), lambda bh, i: (bh, 0, 0)),
    ]
    args = (qh, kh, vh)
    if padding_mask is not None:
        # [B, Nk] keep-mask as f32; each (batch, head) program reads its
        # batch row (index map folds bh → b).
        mk = padding_mask.astype(jnp.float32).reshape(b, 1, nk)
        in_specs.append(
            pl.BlockSpec((None, 1, nk), lambda bh, i: (bh // h, 0, 0)))
        args = args + (mk,)

        def kernel(q_r, k_r, v_r, m_r, o_r, l_r):
            _fwd_kernel(q_r, k_r, v_r, o_r, l_r, scale=scale, causal=causal,
                        block_k=BLOCK_K, mask_ref=m_r)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq // BLOCK_Q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, BLOCK_Q, _LANES), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, nq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, nq, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    out = out.reshape(b, h, nq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(b, h, nq)
    return out, lse


def _bwd_xla(q, k, v, out, lse, dout, scale, causal, padding_mask=None,
             q_chunk=None):
    """Flash-style backward in XLA: recompute P per (b,h) from the saved
    LSE; XLA blocks/fuses the einsums onto the MXU. Long sequences scan
    over query chunks so the transient [B,H,C,Nk] score block stays
    bounded (~512 MiB) instead of materializing the full [B,H,Nq,Nk]
    matrix — this is the memory-escape backward for shapes the Pallas
    kernels' VMEM model rejects (flash_attention_bwd.supported)."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # [B,H,Nq,D]
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    doh = jnp.swapaxes(dout, 1, 2).astype(jnp.float32)
    oh = jnp.swapaxes(out, 1, 2).astype(jnp.float32)
    b, h, nq, d = qh.shape
    nk = kh.shape[2]
    # fully-masked rows carry the sentinel LSE from the forward: exp(s-lse)
    # would be exp(0)=1 per key there — gate p to zero instead so such rows
    # contribute no gradient (matching their zeroed forward output)
    lse = jnp.where(lse > _NEG_INF * 0.1, lse, jnp.inf)

    def block_grads(qs, dos, os_, lses, q0):
        """Gradient contributions of one query block [B,H,C,D]."""
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kh) * scale
        if padding_mask is not None:
            s = jnp.where(padding_mask[:, None, None, :] > 0.5, s,
                          _NEG_INF)
        if causal:
            c = qs.shape[2]
            q_ids = (q0 + (nk - nq) +
                     jax.lax.broadcasted_iota(jnp.int32, (c, nk), 0))
            k_ids = jax.lax.broadcasted_iota(jnp.int32, (c, nk), 1)
            s = jnp.where((q_ids >= k_ids)[None, None], s, _NEG_INF)
        p = jnp.exp(s - lses[..., None])              # [B,H,C,Nk]
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, dos)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dos, vh)
        delta = jnp.sum(dos * os_, axis=-1, keepdims=True)
        ds = p * (dp - delta) * scale
        dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kh)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qs)
        return dq_c, dk_c, dv_c

    # chunk size: bound the f32 score block near 512 MiB, keep the
    # q dim a multiple that divides nq (nq is BLOCK_Q-aligned here);
    # q_chunk overrides for tests
    if q_chunk is not None:
        if nq % q_chunk:
            raise ValueError(
                f"q_chunk={q_chunk} must divide nq={nq} (a non-divisor "
                "would silently drop the tail rows' gradients)")
        chunk = q_chunk
    else:
        target = max(1, (512 * 1024 * 1024) // max(b * h * nk * 4, 1))
        # floor at 128 (nq is BLOCK_Q-aligned on every path that
        # reaches here): for the very largest workloads target drops
        # below every candidate, and falling back to chunk=nq would
        # materialize the full score matrix — the exact OOM this
        # chunking exists to prevent
        chunk = 128 if nq % 128 == 0 else nq
        for cand in (4096, 2048, 1024, 512, 256):
            if cand <= target and nq % cand == 0:
                chunk = cand
                break
    if chunk >= nq:
        dq, dk, dv = block_grads(qh, doh, oh, lse, 0)
    else:
        n_chunks = nq // chunk

        def body(carry, i):
            dk_acc, dv_acc = carry
            sl = lambda a: jax.lax.dynamic_slice_in_dim(
                a, i * chunk, chunk, axis=2)
            dq_c, dk_c, dv_c = block_grads(sl(qh), sl(doh), sl(oh),
                                           sl(lse), i * chunk)
            return (dk_acc + dk_c, dv_acc + dv_c), dq_c
        (dk, dv), dq_chunks = jax.lax.scan(
            body, (jnp.zeros_like(kh), jnp.zeros_like(vh)),
            jnp.arange(n_chunks))
        # [n_chunks, B, H, C, D] -> [B, H, Nq, D]
        dq = jnp.moveaxis(dq_chunks, 0, 2).reshape(b, h, nq, d)
    to = lambda x: jnp.swapaxes(x, 1, 2)
    return (to(dq).astype(q.dtype), to(dk).astype(k.dtype),
            to(dv).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _bwd_dispatch(q, k, v, out, lse, dout, scale, causal,
                  padding_mask=None):
    """XLA recompute backward by default; the Pallas backward kernels
    when the flash_backward flag allows (chip-smoked lowering only —
    see flash_attention_bwd.py)."""
    from ...core.flags import flag_active
    if flag_active("flash_backward"):
        from .flash_attention_bwd import flash_attention_bwd, supported
        if supported(q.shape, k.shape):
            return flash_attention_bwd(q, k, v, out, lse, dout, scale,
                                       causal, padding_mask=padding_mask)
    return _bwd_xla(q, k, v, out, lse, dout, scale, causal,
                    padding_mask=padding_mask)


def _flash_vjp_bwd(scale, causal, res, dout):
    q, k, v, out, lse = res
    return _bwd_dispatch(q, k, v, out, lse, dout, scale, causal)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_masked(q, k, v, padding_mask, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal, padding_mask=padding_mask)
    return out


def _flash_masked_vjp_fwd(q, k, v, padding_mask, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal, padding_mask=padding_mask)
    return out, (q, k, v, padding_mask, out, lse)


def _flash_masked_vjp_bwd(scale, causal, res, dout):
    q, k, v, padding_mask, out, lse = res
    dq, dk, dv = _bwd_dispatch(q, k, v, out, lse, dout, scale, causal,
                               padding_mask=padding_mask)
    # mask enters as f32 0/1 (see flash_attention), so a plain zero
    # cotangent is the right "non-differentiable" answer
    return dq, dk, dv, jnp.zeros_like(padding_mask)


_flash_masked.defvjp(_flash_masked_vjp_fwd, _flash_masked_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, padding_mask=None):
    """Fused attention. ``padding_mask``: optional [B, Nk] keep-mask
    (bool/0-1); padded key positions are excluded from the softmax —
    the Pallas analog of the reference's additive attention-mask input
    (nn/layer/transformer.py MultiHeadAttention attn_mask)."""
    d = q.shape[-1]
    s = float(scale) if scale is not None else float(1.0 / (d ** 0.5))
    if padding_mask is None:
        return _flash(q, k, v, s, causal)
    pm = jnp.asarray(padding_mask)
    if pm.dtype == jnp.bool_:
        pm = pm.astype(jnp.float32)
    return _flash_masked(q, k, v, pm, s, causal)
