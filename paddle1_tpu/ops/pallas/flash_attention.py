"""Flash attention Pallas kernel (stub gate; kernel lands in ops/pallas).

Until the tuned kernel is enabled for a shape, callers use the XLA
composition in nn/functional/attention.py — XLA's own fusion already keeps
the softmax in VMEM for moderate sequence lengths.
"""

from __future__ import annotations


def supported(q_shape, k_shape) -> bool:
    return False


def flash_attention(q, k, v, causal=False):
    raise NotImplementedError("flash kernel gated off; use attention_ref")
