"""Flash attention Pallas kernel (TPU MXU/VMEM-native fused attention).

Replaces the reference's fused multihead attention CUDA kernels
(/root/reference/paddle/fluid/operators/fused/ attention ops) with the
TPU idiom: online-softmax blocking in VMEM, one pass over K/V per query
block, logits never materialized in HBM.

Layout: [B, N, H, D] (paddle layout, matching nn.functional.attention).
Forward = Pallas kernel (+ log-sum-exp residual); backward = XLA
recompute from the LSE (flash-style, no stored probabilities).
Runs in interpreter mode off-TPU so tests exercise the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(q_shape, k_shape, causal: bool = False) -> bool:
    """Tile-aligned shapes only; everything else uses attention_ref."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    _, nq, _, d = q_shape
    _, nk, _, _ = k_shape
    if nq % BLOCK_Q or nk % BLOCK_K:
        return False
    if causal and nq > nk:
        # bottom-right causal leaves leading queries with ZERO visible
        # keys; the zero-sumexp sentinel would poison the vjp — let
        # attention_ref handle this degenerate alignment
        return False
    if d % 8 or d > 256:
        return False
    # K+V rows for one (batch, head) must fit in VMEM comfortably.
    if 2 * nk * d * 4 > 8 * 1024 * 1024:
        return False
    return True


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k):
    # q_ref: [BLOCK_Q, D]; k_ref/v_ref: [N_k, D]; o_ref: [BLOCK_Q, D]
    q_blk = pl.program_id(1)
    nk = k_ref.shape[0]
    nq = pl.num_programs(1) * BLOCK_Q
    d = q_ref.shape[1]
    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            # bottom-right alignment (query i attends keys j <= i + nk-nq),
            # matching attention_ref's tril(..., nk - nq)
            q_ids = (q_blk * BLOCK_Q + (nk - nq) +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (BLOCK_Q, block_k), 0))
            k_ids = (i * block_k +
                     jax.lax.broadcasted_iota(jnp.int32,
                                              (BLOCK_Q, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((BLOCK_Q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((BLOCK_Q,), jnp.float32)
    acc0 = jnp.zeros((BLOCK_Q, d), jnp.float32)
    n_blocks = nk // block_k
    if causal:
        # blocks strictly above the (aligned) diagonal contribute nothing
        hi = (q_blk + 1) * BLOCK_Q + (nk - nq)
        n_blocks_eff = jnp.minimum(n_blocks, pl.cdiv(hi, block_k))
        m, l, acc = jax.lax.fori_loop(0, n_blocks_eff, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal):
    b, nq, h, d = q.shape
    nk = k.shape[1]
    # [B, N, H, D] → [B*H, N, D]
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, nq, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, nk, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, nk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=BLOCK_K)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq // BLOCK_Q),
        in_specs=[
            pl.BlockSpec((None, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, nk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((None, nk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, BLOCK_Q), lambda bh, i: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, nq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, nq), jnp.float32),
        ],
        interpret=_interpret(),
    )(qh, kh, vh)
    out = out.reshape(b, h, nq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, nq)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, res, dout):
    """Flash-style backward in XLA: recompute P per (b,h) from the saved
    LSE; XLA blocks/fuses the einsums onto the MXU. (A hand-written Pallas
    backward kernel is a later-round optimization.)"""
    q, k, v, out, lse = res
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # [B,H,Nq,D]
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    doh = jnp.swapaxes(dout, 1, 2).astype(jnp.float32)
    oh = jnp.swapaxes(out, 1, 2).astype(jnp.float32)

    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        nq, nk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((nq, nk), bool), nk - nq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])                   # [B,H,Nq,Nk]
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, doh)
    dp = jnp.einsum("bhqd,bhkd->bhqk", doh, vh)
    delta = jnp.sum(doh * oh, axis=-1, keepdims=True)  # [B,H,Nq,1]
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kh)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qh)
    to = lambda x: jnp.swapaxes(x, 1, 2)
    return (to(dq).astype(q.dtype), to(dk).astype(k.dtype),
            to(dv).astype(v.dtype))


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    d = q.shape[-1]
    s = float(scale) if scale is not None else float(1.0 / (d ** 0.5))
    return _flash(q, k, v, s, causal)
