"""Fused LayerNorm Pallas kernel.

TPU-native analog of the reference's fused LayerNorm CUDA kernels
(/root/reference/paddle/fluid/operators/fused/fused_layernorm_* and
layer_norm_op.cu): one VMEM pass computes mean/rstd and the normalized,
affine-transformed output per row — no separate stats kernels, no HBM
round-trips for intermediates.

Forward = Pallas kernel; backward = XLA composition that recomputes the
(cheap, fusable) row stats — the same residual-free flash-style split used
by ops/pallas/flash_attention.py. Runs in interpreter mode off-TPU so tests
exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import block_rows as _block_rows, interpret as _interpret

__all__ = ["fused_layer_norm", "supported"]


def supported(shape, n_norm_axes: int) -> bool:
    """One trailing normalized axis, lane-aligned, rows sublane-aligned,
    and a row block that fits the VMEM budget at this h."""
    if n_norm_axes != 1 or len(shape) < 2:
        return False
    h = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    if h % 128:
        return False
    return _block_rows(rows, h) > 0


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)            # [BR, H]
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _ln_fwd(x2, w, b, eps):
    rows, h = x2.shape
    br = _block_rows(rows, h)
    kernel = functools.partial(_ln_fwd_kernel, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x2.dtype),
        interpret=_interpret(),
    )(x2, w.reshape(1, h), b.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln(x2, w, b, eps):
    return _ln_fwd(x2, w, b, eps)


def _ln_vjp_fwd(x2, w, b, eps):
    return _ln_fwd(x2, w, b, eps), (x2, w, b)


def _ln_vjp_bwd(eps, res, dy):
    x2, w, b = res
    xf = x2.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    mean = jnp.mean(xf, axis=1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dyw = dyf * w.astype(jnp.float32)[None, :]
    dx = rstd * (dyw - jnp.mean(dyw, axis=1, keepdims=True)
                 - xhat * jnp.mean(dyw * xhat, axis=1, keepdims=True))
    dw = jnp.sum(dyf * xhat, axis=0)
    db = jnp.sum(dyf, axis=0)
    return (dx.astype(x2.dtype), dw.astype(w.dtype), db.astype(b.dtype))


_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layer_norm(x, weight, bias, epsilon: float = 1e-5):
    """LayerNorm over the last axis. x: [..., H]; weight/bias: [H]."""
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    y = _ln(x2, weight, bias, float(epsilon))
    return y.reshape(x.shape)
