"""Shared helpers for the Pallas kernel family (one definition — the
VMEM budget, row-block ladder, and backend check must not drift between
kernels)."""

from __future__ import annotations

import jax

_VMEM_BUDGET = 4 * 1024 * 1024  # input block + output block, f32


def interpret() -> bool:
    """Run the kernel in interpreter mode off-TPU so tests exercise the
    same code path the chip executes."""
    return jax.default_backend() != "tpu"


def block_rows(rows: int, h: int) -> int:
    """Largest sublane-aligned row block whose [br, h] f32 in+out blocks
    fit the VMEM budget; 0 if none divides ``rows``."""
    if h <= 0:
        return 0
    for br in (256, 128, 64, 32, 16, 8):
        if rows % br == 0 and br * h * 4 * 2 <= _VMEM_BUDGET:
            return br
    return 0
