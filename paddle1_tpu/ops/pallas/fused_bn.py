"""Fused batch-norm Pallas kernels (forward family).

TPU-native analog of the reference's fused BN CUDA ops
(/root/reference/paddle/fluid/operators/fused/fused_bn_activation_op.cu
and fused_bn_add_activation_op.cu): ONE kernel owns the whole
stats + normalize + activation (+ residual-add) chain instead of the
multi-pass XLA lowering the ResNet-50 step trace pins ~46% of on-chip
time on (multiply_reduce / convert_reduce / multiply_subtract fusions,
chip_results/resnet_trace_b32.txt).

The training kernel is a two-pass-in-one-call design: a sequential
(2, row_blocks) grid whose first phase accumulates per-channel
sum / sum-of-squares into the f32 stat outputs resident in VMEM and
whose second phase finalizes mean/var once and streams the normalized,
affine-transformed, optionally residual-added and activated output.
No stat intermediate ever round-trips HBM, and the output (and
residual) windows ride a ``p * i`` index map so they stay parked on
block 0 through the stats phase — the data moves x twice, y and the
residual once.

bf16-safe exact-count discipline (the one ``SyncBatchNorm`` documents):
every reduction accumulates in f32 regardless of the compute dtype, and
the element count enters once as an exact host-side constant — a bf16
count is inexact past 256 and E[x^2]-mean^2 cancels catastrophically,
so the variance is clamped at 0 the same way ``sync_batch_norm_op``
does.

Inputs are channels-last ``[rows, C]`` (NHWC flattened), so under
``conv_nhwc=auto`` the conv/BN/act/pool residual block stays
layout-stable end to end. Backward lives in ``fused_bn_bwd.py``
(Pallas one-pass dx/dgamma/dbeta behind ``fused_bn_bwd``, with the XLA
composition as the reference/ablation path). Interpret mode runs the
same kernels on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import block_rows as _block_rows, interpret as _interpret

__all__ = ["supported", "fused_bn_train", "fused_bn_norm",
           "local_moments", "ACTS"]

ACTS = ("identity", "relu")


def _check_act(act: str) -> None:
    if act not in ACTS:
        from ...core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"fused_bn activation must be one of {ACTS}, got {act!r}")


def supported(shape, dtype=None) -> bool:
    """Channels-last input ``[..., C]``: lane-friendly channel count,
    rows tiling into the shared VMEM row-block ladder (and a sublane-
    aligned block for 16-bit compute dtypes)."""
    if len(shape) < 2:
        return False
    c = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    if c <= 0 or c % 8:
        return False
    br = _block_rows(rows, c)
    if br <= 0:
        return False
    if dtype is not None and jnp.dtype(dtype).itemsize == 2 and br % 16:
        return False
    return True


def _act_fwd(y, act: str):
    if act == "relu":
        return jnp.maximum(y, 0.0)
    return y


# ---------------------------------------------------------------------------
# Training kernel: stats + normalize + act (+ residual) in one call
# ---------------------------------------------------------------------------


def _bn_train_kernel(*refs, eps, act, inv_count, with_res):
    if with_res:
        x_ref, g_ref, b_ref, r_ref, y_ref, mean_ref, var_ref = refs
    else:
        x_ref, g_ref, b_ref, y_ref, mean_ref, var_ref = refs
        r_ref = None
    p = pl.program_id(0)
    i = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)                      # [BR, C]

    @pl.when(p == 0)
    def _accumulate():
        s = jnp.sum(x, axis=0, keepdims=True)
        ss = jnp.sum(x * x, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _():
            mean_ref[:] = s
            var_ref[:] = ss

        @pl.when(i != 0)
        def _():
            mean_ref[:] = mean_ref[:] + s
            var_ref[:] = var_ref[:] + ss

    @pl.when(p == 1)
    def _normalize():
        @pl.when(i == 0)
        def _finalize():
            m = mean_ref[:] * inv_count
            var_ref[:] = jnp.maximum(var_ref[:] * inv_count - m * m, 0.0)
            mean_ref[:] = m

        y = (x - mean_ref[:]) * jax.lax.rsqrt(var_ref[:] + eps)
        y = y * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
        if r_ref is not None:
            y = y + r_ref[:].astype(jnp.float32)
        y_ref[:] = _act_fwd(y, act).astype(y_ref.dtype)


def _train_fwd(x2, g, b, res, eps, act):
    rows, c = x2.shape
    br = _block_rows(rows, c)
    kernel = functools.partial(
        _bn_train_kernel, eps=eps, act=act, inv_count=1.0 / rows,
        with_res=res is not None)
    in_specs = [
        pl.BlockSpec((br, c), lambda p, i: (i, 0)),
        pl.BlockSpec((1, c), lambda p, i: (0, 0)),
        pl.BlockSpec((1, c), lambda p, i: (0, 0)),
    ]
    args = [x2, g.reshape(1, c), b.reshape(1, c)]
    if res is not None:
        # parked on block 0 through the stats phase (fetched once),
        # streamed in lockstep with x through the normalize phase
        in_specs.append(pl.BlockSpec((br, c), lambda p, i: (p * i, 0)))
        args.append(res)
    y, mean, var = pl.pallas_call(
        kernel,
        grid=(2, rows // br),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((br, c), lambda p, i: (p * i, 0)),
            pl.BlockSpec((1, c), lambda p, i: (0, 0)),
            pl.BlockSpec((1, c), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, c), x2.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return y, mean.reshape(c), var.reshape(c)


def _stat_cotangent_terms(x2, mean, dmean, dvar, inv_count):
    """Fold cotangents that flow INTO the batch-stat outputs back into
    dx (rare — running-stat consumers detach the stats, so these are
    zeros on the training path and XLA folds the broadcast away under
    jit): mean = sum(x)/n, var = sum(x^2)/n - mean^2."""
    xf = x2.astype(jnp.float32)
    extra = (dmean[None, :]
             + 2.0 * dvar[None, :] * (xf - mean[None, :])) * inv_count
    return extra


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x2, g, b, eps, act):
    return _train_fwd(x2, g, b, None, eps, act)


def _bn_train_fwd_rule(x2, g, b, eps, act):
    y, mean, var = _train_fwd(x2, g, b, None, eps, act)
    return (y, mean, var), (x2, g, mean, var, y)


def _bn_train_bwd_rule(eps, act, resids, cts):
    x2, g, mean, var, y = resids
    dy, dmean, dvar = cts
    from .fused_bn_bwd import train_bwd
    dx, dg, db = train_bwd(x2, g, mean, var, y, dy, eps, act)
    extra = _stat_cotangent_terms(x2, mean, dmean, dvar, 1.0 / x2.shape[0])
    dx = (dx.astype(jnp.float32) + extra).astype(x2.dtype)
    return dx, dg.astype(g.dtype), db.astype(g.dtype)


_bn_train.defvjp(_bn_train_fwd_rule, _bn_train_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_train_res(x2, g, b, res, eps, act):
    return _train_fwd(x2, g, b, res, eps, act)


def _bn_train_res_fwd_rule(x2, g, b, res, eps, act):
    y, mean, var = _train_fwd(x2, g, b, res, eps, act)
    # zero-size carrier: residuals must be jax types, and bwd only
    # needs the residual's dtype
    return (y, mean, var), (x2, g, mean, var, y,
                            jnp.zeros((0,), res.dtype))


def _bn_train_res_bwd_rule(eps, act, resids, cts):
    x2, g, mean, var, y, res_proto = resids
    dy, dmean, dvar = cts
    from .fused_bn_bwd import train_bwd
    dx, dg, db, dres = train_bwd(x2, g, mean, var, y, dy, eps, act,
                                 with_res=True)
    extra = _stat_cotangent_terms(x2, mean, dmean, dvar, 1.0 / x2.shape[0])
    dx = (dx.astype(jnp.float32) + extra).astype(x2.dtype)
    return (dx, dg.astype(g.dtype), db.astype(g.dtype),
            dres.astype(res_proto.dtype))


_bn_train_res.defvjp(_bn_train_res_fwd_rule, _bn_train_res_bwd_rule)


def fused_bn_train(x2, gamma, beta, epsilon, act="identity", residual=None):
    """Training-mode fused BN over channels-last ``x2: [rows, C]``.

    Returns ``(y, batch_mean, batch_var)`` with the stats in f32 —
    ``y = act((x - mean) * rsqrt(var + eps) * gamma + beta [+ residual])``.
    """
    _check_act(act)
    if residual is None:
        return _bn_train(x2, gamma, beta, float(epsilon), act)
    return _bn_train_res(x2, gamma, beta, residual, float(epsilon), act)


# ---------------------------------------------------------------------------
# Normalize kernel: given stats (eval mode / SyncBatchNorm post-psum)
# ---------------------------------------------------------------------------


def _bn_norm_kernel(*refs, eps, act, with_res):
    if with_res:
        x_ref, m_ref, v_ref, g_ref, b_ref, r_ref, y_ref = refs
    else:
        x_ref, m_ref, v_ref, g_ref, b_ref, y_ref = refs
        r_ref = None
    x = x_ref[:].astype(jnp.float32)
    y = (x - m_ref[:]) * jax.lax.rsqrt(v_ref[:] + eps)
    y = y * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    if r_ref is not None:
        y = y + r_ref[:].astype(jnp.float32)
    y_ref[:] = _act_fwd(y, act).astype(y_ref.dtype)


def _norm_fwd(x2, m, v, g, b, res, eps, act):
    rows, c = x2.shape
    br = _block_rows(rows, c)
    kernel = functools.partial(_bn_norm_kernel, eps=eps, act=act,
                               with_res=res is not None)
    row_spec = pl.BlockSpec((br, c), lambda i: (i, 0))
    ch_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    in_specs = [row_spec, ch_spec, ch_spec, ch_spec, ch_spec]
    args = [x2, m.astype(jnp.float32).reshape(1, c),
            v.astype(jnp.float32).reshape(1, c),
            g.reshape(1, c), b.reshape(1, c)]
    if res is not None:
        in_specs.append(row_spec)
        args.append(res)
    return pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows, c), x2.dtype),
        interpret=_interpret(),
    )(*args)


def _norm_stat_grads(g, var, dg, db, eps):
    """Channel-sized cotangents for the given stats: y depends on mean
    only through the shift and on var only through rstd."""
    gf = g.astype(jnp.float32)
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    dm = -gf * rstd * db
    dv = -0.5 * gf * rstd * rstd * rstd * (dg / rstd)
    return dm, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _bn_norm(x2, m, v, g, b, eps, act):
    return _norm_fwd(x2, m, v, g, b, None, eps, act)


def _bn_norm_fwd_rule(x2, m, v, g, b, eps, act):
    y = _norm_fwd(x2, m, v, g, b, None, eps, act)
    return y, (x2, m, v, g, y)


def _bn_norm_bwd_rule(eps, act, resids, dy):
    x2, m, v, g, y = resids
    from .fused_bn_bwd import norm_bwd
    dx, dg, db = norm_bwd(x2, g, m, v, y, dy, eps, act)
    dm, dv = _norm_stat_grads(g, v, dg, db, eps)
    return (dx, dm.astype(m.dtype), dv.astype(v.dtype),
            dg.astype(g.dtype), db.astype(g.dtype))


_bn_norm.defvjp(_bn_norm_fwd_rule, _bn_norm_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _bn_norm_res(x2, m, v, g, b, res, eps, act):
    return _norm_fwd(x2, m, v, g, b, res, eps, act)


def _bn_norm_res_fwd_rule(x2, m, v, g, b, res, eps, act):
    y = _norm_fwd(x2, m, v, g, b, res, eps, act)
    return y, (x2, m, v, g, y, jnp.zeros((0,), res.dtype))


def _bn_norm_res_bwd_rule(eps, act, resids, dy):
    x2, m, v, g, y, res_proto = resids
    from .fused_bn_bwd import norm_bwd
    dx, dg, db, dres = norm_bwd(x2, g, m, v, y, dy, eps, act,
                                with_res=True)
    dm, dv = _norm_stat_grads(g, v, dg, db, eps)
    return (dx, dm.astype(m.dtype), dv.astype(v.dtype),
            dg.astype(g.dtype), db.astype(g.dtype),
            dres.astype(res_proto.dtype))


_bn_norm_res.defvjp(_bn_norm_res_fwd_rule, _bn_norm_res_bwd_rule)


def fused_bn_norm(x2, mean, var, gamma, beta, epsilon, act="identity",
                  residual=None):
    """Normalize ``x2: [rows, C]`` with GIVEN per-channel stats — the
    eval-mode kernel, and SyncBatchNorm's normalize after its
    cross-replica stat reduction (mean/var stay differentiable so the
    psum transpose sees their cotangents)."""
    _check_act(act)
    if residual is None:
        return _bn_norm(x2, mean, var, gamma, beta, float(epsilon), act)
    return _bn_norm_res(x2, mean, var, gamma, beta, residual,
                        float(epsilon), act)


# ---------------------------------------------------------------------------
# Local moments: SyncBatchNorm's per-replica stat pass
# ---------------------------------------------------------------------------


def _moments_kernel(x_ref, s_ref, ss_ref):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    s = jnp.sum(x, axis=0, keepdims=True)
    ss = jnp.sum(x * x, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        s_ref[:] = s
        ss_ref[:] = ss

    @pl.when(i != 0)
    def _():
        s_ref[:] = s_ref[:] + s
        ss_ref[:] = ss_ref[:] + ss


def _moments_fwd(x2):
    rows, c = x2.shape
    br = _block_rows(rows, c)
    s, ss = pl.pallas_call(
        _moments_kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0)),
                   pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        interpret=_interpret(),
    )(x2)
    return s.reshape(c), ss.reshape(c)


@jax.custom_vjp
def _lm(x2):
    return _moments_fwd(x2)


def _lm_fwd_rule(x2):
    return _moments_fwd(x2), x2


def _lm_bwd_rule(x2, cts):
    ds, dss = cts
    dx = ds[None, :] + 2.0 * x2.astype(jnp.float32) * dss[None, :]
    return (dx.astype(x2.dtype),)


_lm.defvjp(_lm_fwd_rule, _lm_bwd_rule)


def local_moments(x2):
    """One f32 pass over ``x2: [rows, C]`` returning per-channel
    ``(sum, sum_of_squares)`` — the local half of SyncBatchNorm's
    cross-replica stats."""
    return _lm(x2)
