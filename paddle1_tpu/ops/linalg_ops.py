"""Eager op surface: linear algebra.

Analog of /root/reference/paddle/fluid/operators/{matmul_v2,cholesky,svd,
inverse,...}_op.cc and python/paddle/tensor/linalg.py. Dense decompositions
lower to XLA's native LAPACK-style custom calls (QR/Cholesky/SVD all have
TPU lowerings via jax.numpy.linalg).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "cholesky", "inv", "pinv", "svd", "qr", "lu", "matrix_power", "det",
    "slogdet", "solve", "triangular_solve", "cholesky_solve", "lstsq",
    "eig", "eigh", "eigvals", "eigvalsh", "norm", "dist", "cond",
    "matrix_rank", "multi_dot", "cov", "corrcoef", "householder_product",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def cholesky(x, upper=False, name=None):
    def f(x):
        l = jnp.linalg.cholesky(x)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply("cholesky", f, (_t(x),))


def inv(x, name=None):
    return apply("inv", jnp.linalg.inv, (_t(x),))


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda x: jnp.linalg.pinv(x, rtol=rcond,
                                                   hermitian=hermitian),
                 (_t(x),))


def svd(x, full_matrices=False, name=None):
    return apply("svd",
                 lambda x: jnp.linalg.svd(x, full_matrices=full_matrices),
                 (_t(x),), n_outputs=3)


def qr(x, mode="reduced", name=None):
    return apply("qr", lambda x: jnp.linalg.qr(x, mode=mode), (_t(x),),
                 n_outputs=2)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(x):
        lu_, piv = jax.scipy.linalg.lu_factor(x)
        return lu_, piv.astype(jnp.int32)
    outs = apply("lu", f, (_t(x),), n_outputs=2)
    if get_infos:
        info = to_tensor(np.zeros(_t(x).shape[:-2], np.int32))
        return (*outs, info)
    return outs


def matrix_power(x, n, name=None):
    return apply("matrix_power",
                 lambda x: jnp.linalg.matrix_power(x, n), (_t(x),))


def det(x, name=None):
    return apply("det", jnp.linalg.det, (_t(x),))


def slogdet(x, name=None):
    def f(x):
        sign, logdet = jnp.linalg.slogdet(x)
        return jnp.stack([sign, logdet], axis=0)
    return apply("slogdet", f, (_t(x),))


def solve(x, y, name=None):
    def f(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)
    return apply("solve", f, (_t(x), _t(y)))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply("triangular_solve", f, (_t(x), _t(y)))


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return apply("cholesky_solve", f, (_t(x), _t(y)))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(jnp.int32), sv
    return apply("lstsq", f, (_t(x), _t(y)), n_outputs=4)


def eig(x, name=None):
    # General (non-symmetric) eig has no TPU lowering; run on host like the
    # reference runs LAPACK on CPU for the same op.
    arr = _t(x).numpy()
    w, v = np.linalg.eig(arr)
    return to_tensor(w), to_tensor(v)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda x: jnp.linalg.eigh(x, UPLO=UPLO), (_t(x),),
                 n_outputs=2)


def eigvals(x, name=None):
    arr = _t(x).numpy()
    return to_tensor(np.linalg.eigvals(arr))


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda x: jnp.linalg.eigvalsh(x, UPLO=UPLO),
                 (_t(x),))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(x):
        if p in (None, "fro") and axis is None:
            return jnp.sqrt(jnp.sum(x * x))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p in (None, "fro"):
            return jnp.sqrt(jnp.sum(x * x, axis=ax, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(x), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(x), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((x != 0).astype(x.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(x) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply("norm", f, (_t(x),))


def dist(x, y, p=2, name=None):
    return norm(_t(x) - _t(y), p=p)


def cond(x, p=None, name=None):
    return apply("cond", lambda x: jnp.linalg.cond(x, p=p), (_t(x),))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def f(x):
        return jnp.linalg.matrix_rank(x, rtol=tol).astype(jnp.int64)
    return apply("matrix_rank", f, (_t(x),))


def multi_dot(x, name=None):
    return apply("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs),
                 tuple(_t(e) for e in x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def f(x):
        return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0)
    return apply("cov", f, (_t(x),))


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda x: jnp.corrcoef(x, rowvar=rowvar),
                 (_t(x),))


def householder_product(x, tau, name=None):
    def f(a, tau):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, (*a.shape[:-2], m, m)).copy() \
            if a.ndim > 2 else q
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype),
                                 a[..., i + 1:, i]], axis=-1)
            h = jnp.eye(m, dtype=a.dtype) - tau[..., i, None, None] * \
                (v[..., :, None] * v[..., None, :])
            q = q @ h
        return q[..., :, :n]
    return apply("householder_product", f, (_t(x), _t(tau)))
