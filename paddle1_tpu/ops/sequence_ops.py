"""Sequence ops over dense-plus-lengths ragged batches.

Analog of the reference's LoDTensor sequence op family
(/root/reference/paddle/fluid/operators/sequence_ops/, 6.2k LoC). The
LoD (level-of-detail offsets) representation is CPU-pointer-chasing by
design and hostile to XLA's static shapes; the TPU-native mapping (SURVEY
§7 hard part d) is a dense [batch, max_len, ...] tensor plus an int
``lengths`` vector — every op below is a masked dense computation that
jits cleanly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_expand",
           "sequence_reverse", "sequence_concat", "sequence_first_step",
           "sequence_last_step", "sequence_conv", "sequence_enumerate",
           "sequence_erase", "sequence_expand_as", "sequence_reshape",
           "sequence_scatter", "sequence_slice",
           "sequence_topk_avg_pooling"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths → [batch, maxlen] 0/1 mask (reference sequence_mask_op)."""
    maxlen_static = maxlen

    def f(lengths):
        ml = maxlen_static if maxlen_static is not None else int(
            jnp.max(lengths))
        ids = jnp.arange(ml)[None, :]
        return (ids < lengths[:, None]).astype(jnp.dtype(dtype))
    return apply("sequence_mask", f, (_t(x),))


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """Flat packed rows [sum(len), ...] + lengths → dense
    [batch, maxlen, ...] (reference sequence_pad_op). Returns (padded,
    lengths)."""
    lengths_np = np.asarray(lengths.numpy() if isinstance(lengths, Tensor)
                            else lengths).astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(lengths_np)])
    ml = int(maxlen) if maxlen is not None else int(lengths_np.max())

    def f(flat, pv):
        rows = []
        for b, ln in enumerate(lengths_np):
            seg = flat[offsets[b]:offsets[b + 1]]
            pad_shape = (ml - int(ln),) + flat.shape[1:]
            pad = jnp.full(pad_shape, pv, flat.dtype)
            rows.append(jnp.concatenate([seg, pad], axis=0))
        return jnp.stack(rows)
    padded = apply("sequence_pad", f, (_t(x), _t(pad_value)))
    return padded, to_tensor(lengths_np)


def sequence_unpad(x, length, name=None):
    """Dense [batch, maxlen, ...] + lengths → flat packed rows
    (reference sequence_unpad_op)."""
    lengths_np = np.asarray(length.numpy() if isinstance(length, Tensor)
                            else length).astype(np.int64)

    def f(dense):
        segs = [dense[b, :int(ln)] for b, ln in enumerate(lengths_np)]
        return jnp.concatenate(segs, axis=0)
    return apply("sequence_unpad", f, (_t(x),))


def sequence_pool(x, lengths, pool_type="sum", name=None):
    """Masked pooling over the time dim (reference sequence_pool_op):
    sum/average/sqrt/max/first/last."""
    pool_type = pool_type.lower()

    def f(dense, lengths):
        ml = dense.shape[1]
        mask = (jnp.arange(ml)[None, :] < lengths[:, None])
        mexp = mask.reshape(mask.shape + (1,) * (dense.ndim - 2))
        if pool_type == "sum":
            return jnp.sum(jnp.where(mexp, dense, 0), axis=1)
        if pool_type in ("average", "mean"):
            s = jnp.sum(jnp.where(mexp, dense, 0), axis=1)
            return s / jnp.maximum(lengths, 1).astype(dense.dtype).reshape(
                (-1,) + (1,) * (dense.ndim - 2))
        if pool_type == "sqrt":
            s = jnp.sum(jnp.where(mexp, dense, 0), axis=1)
            return s / jnp.sqrt(jnp.maximum(lengths, 1).astype(
                dense.dtype)).reshape((-1,) + (1,) * (dense.ndim - 2))
        if pool_type == "max":
            neg = jnp.finfo(dense.dtype).min if jnp.issubdtype(
                dense.dtype, jnp.floating) else jnp.iinfo(dense.dtype).min
            return jnp.max(jnp.where(mexp, dense, neg), axis=1)
        if pool_type == "first":
            return dense[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(lengths - 1, 0)
            return jnp.take_along_axis(
                dense, idx.reshape((-1, 1) + (1,) * (dense.ndim - 2)),
                axis=1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")
    return apply("sequence_pool", f, (_t(x), _t(lengths)))


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over time (reference sequence_softmax_op)."""

    def f(dense, lengths):
        ml = dense.shape[1]
        mask = (jnp.arange(ml)[None, :] < lengths[:, None])
        mexp = mask.reshape(mask.shape + (1,) * (dense.ndim - 2))
        neg = jnp.finfo(dense.dtype).min
        masked = jnp.where(mexp, dense, neg)
        out = jax.nn.softmax(masked, axis=1)
        return jnp.where(mexp, out, 0)
    return apply("sequence_softmax", f, (_t(x), _t(lengths)))


def sequence_expand(x, lengths, name=None):
    """Repeat row b of x lengths[b] times along a new packed dim
    (reference sequence_expand_op dense analog)."""
    lengths_np = np.asarray(lengths.numpy() if isinstance(lengths, Tensor)
                            else lengths).astype(np.int64)

    def f(dense):
        return jnp.repeat(dense, jnp.asarray(lengths_np), axis=0,
                          total_repeat_length=int(lengths_np.sum()))
    return apply("sequence_expand", f, (_t(x),))


def sequence_reverse(x, lengths, name=None):
    """Reverse each row's valid prefix, keeping padding in place
    (reference sequence_reverse_op)."""

    def f(dense, lengths):
        ml = dense.shape[1]
        ids = jnp.arange(ml)[None, :]
        rev = lengths[:, None] - 1 - ids
        idx = jnp.where(ids < lengths[:, None], rev, ids)
        return jnp.take_along_axis(
            dense, idx.reshape(idx.shape + (1,) * (dense.ndim - 2)),
            axis=1)
    return apply("sequence_reverse", f, (_t(x), _t(lengths)))


def sequence_concat(xs, lengths_list, name=None):
    """Interleave several packed sequences batch-row-wise (reference
    sequence_concat_op): row b of the result is the concatenation of row b
    from each input. Returns (packed, lengths)."""
    ls = [np.asarray(l.numpy() if isinstance(l, Tensor) else l, np.int64)
          for l in lengths_list]
    offs = [np.concatenate([[0], np.cumsum(l)]) for l in ls]
    batch = len(ls[0])

    def f(*flats):
        rows = []
        for b in range(batch):
            for flat, off, l in zip(flats, offs, ls):
                rows.append(flat[off[b]:off[b] + int(l[b])])
        return jnp.concatenate(rows, axis=0)
    packed = apply("sequence_concat", f, tuple(_t(x) for x in xs))
    return packed, to_tensor(np.sum(ls, axis=0))


def sequence_conv(x, lengths, filter, context_length, context_start=None,
                  bias=None, name=None):
    """Context-window convolution over time (reference
    sequence_conv_op): each position's context [t+start, t+start+L) is
    concatenated feature-wise and projected by ``filter``
    [L*D, out]. Out-of-sequence context rows are zeros; positions past
    ``lengths`` zero out. ``context_start`` defaults to the centered
    window -(L-1)//2 like the reference's common usage."""
    L = int(context_length)
    start = -((L - 1) // 2) if context_start is None else int(context_start)

    def f(dense, lengths, w, *maybe_b):
        B, T = dense.shape[0], dense.shape[1]
        ids = jnp.arange(T)[None, :]
        valid = ids < lengths[:, None]
        ctx = []
        for off in range(start, start + L):
            src = ids + off
            ok = (src >= 0) & (src < lengths[:, None])
            safe = jnp.clip(src, 0, T - 1)
            shifted = jnp.take_along_axis(
                dense, safe[..., None].repeat(dense.shape[2], -1), axis=1)
            ctx.append(jnp.where(ok[..., None], shifted, 0.0))
        feats = jnp.concatenate(ctx, axis=-1)          # [B, T, L*D]
        out = feats @ w
        if maybe_b:
            out = out + maybe_b[0]
        return jnp.where(valid[..., None], out, 0.0)
    args = (_t(x), _t(lengths), _t(filter)) + (
        (_t(bias),) if bias is not None else ())
    return apply("sequence_conv", f, args)


def sequence_enumerate(x, lengths, win_size, pad_value=0, name=None):
    """Sliding windows of ids (reference sequence_enumerate_op):
    [B, T] → [B, T, win]; window cells past the row's length fill with
    ``pad_value``."""
    W = int(win_size)

    def f(ids, lengths):
        T = ids.shape[1]
        pos = jnp.arange(T)[None, :, None] + jnp.arange(W)[None, None, :]
        ok = pos < lengths[:, None, None]
        safe = jnp.clip(pos, 0, T - 1)
        win = jnp.take_along_axis(ids[:, :, None].repeat(W, -1), safe,
                                  axis=1)
        win = jnp.where(ok, win, pad_value)
        # positions at/after the row length are all-pad
        valid_row = jnp.arange(T)[None, :, None] < lengths[:, None, None]
        return jnp.where(valid_row, win, pad_value)
    return apply("sequence_enumerate", f, (_t(x), _t(lengths)))


def sequence_erase(x, lengths, tokens, name=None):
    """Remove every occurrence of ``tokens`` from each row's valid
    prefix, compacting left (reference sequence_erase_op). Returns
    (dense, new_lengths); freed tail cells are 0."""
    toks = np.asarray(tokens, np.int64).reshape(-1)

    def f(ids, lengths):
        T = ids.shape[1]
        pos = jnp.arange(T)[None, :]
        in_len = pos < lengths[:, None]
        erase = jnp.zeros_like(ids, dtype=bool)
        for t in toks.tolist():
            erase |= ids == t
        keep = in_len & ~erase
        # stable order: kept cells first, original order preserved
        order = jnp.argsort(~keep, axis=1, stable=True)
        compacted = jnp.take_along_axis(ids, order, axis=1)
        new_len = keep.sum(axis=1)
        live = pos < new_len[:, None]
        return jnp.where(live, compacted, 0), new_len
    out, nl = apply("sequence_erase", f, (_t(x), _t(lengths)),
                    n_outputs=2)
    return out, nl


def sequence_expand_as(x, lengths, name=None):
    """Repeat row b of x lengths[b] times (reference
    sequence_expand_as_op — the lengths come from the reference's y
    LoD; here they are explicit)."""
    return sequence_expand(x, lengths, name=name)


def sequence_reshape(x, lengths, new_dim, name=None):
    """Re-chunk each row's flat data to width ``new_dim`` (reference
    sequence_reshape_op): row b's len[b]*D values become
    len[b]*D/new_dim rows. Every len[b]*D must divide new_dim-evenly.
    Returns (dense [B, T*D//new_dim, new_dim], new_lengths)."""
    nd = int(new_dim)

    ln = lengths.numpy() if isinstance(lengths, Tensor) else lengths
    ln_np = np.asarray(ln) if not hasattr(ln, "aval") else None
    if ln_np is not None:
        D_in = _t(x).shape[-1]
        bad = ln_np[(ln_np * D_in) % nd != 0]
        if bad.size:
            raise ValueError(
                f"sequence_reshape: every lengths[b]*D must divide "
                f"new_dim={nd}; rows with lengths {bad.tolist()} "
                f"(D={D_in}) do not — their tail values would be "
                "silently dropped")

    def f(dense, lengths):
        B, T, D = dense.shape
        if (T * D) % nd:
            raise ValueError(f"T*D={T * D} not divisible by {nd}")
        out = dense.reshape(B, (T * D) // nd, nd)
        new_len = lengths * D // nd
        pos = jnp.arange(out.shape[1])[None, :]
        return jnp.where(pos[..., None] < new_len[:, None, None], out,
                         0), new_len
    out, nl = apply("sequence_reshape", f, (_t(x), _t(lengths)),
                    n_outputs=2)
    return out, nl


def sequence_scatter(x, index, updates, lengths, name=None):
    """Scatter-ADD updates into per-row positions (reference
    sequence_scatter_op): x [B, T], index/updates [B, S]; update s of
    row b lands at x[b, index[b, s]] for s < lengths[b]."""

    def f(dense, idx, upd, lengths):
        S = idx.shape[1]
        ok = jnp.arange(S)[None, :] < lengths[:, None]
        upd = jnp.where(ok, upd, 0)
        b_ids = jnp.arange(dense.shape[0])[:, None].repeat(S, 1)
        return dense.at[b_ids.reshape(-1),
                        idx.reshape(-1)].add(upd.reshape(-1))
    return apply("sequence_scatter", f,
                 (_t(x), _t(index), _t(updates), _t(lengths)))


def sequence_slice(x, offset, length, name=None):
    """Per-row subsequence (reference sequence_slice_op): row b keeps
    [offset[b], offset[b]+length[b]). Output is dense
    [B, max(length), ...] (freed cells 0) plus the new lengths."""
    off_np = np.asarray(offset.numpy() if isinstance(offset, Tensor)
                        else offset, np.int64).reshape(-1)
    len_np = np.asarray(length.numpy() if isinstance(length, Tensor)
                        else length, np.int64).reshape(-1)
    T_in = _t(x).shape[1]
    if ((off_np < 0).any() or (len_np < 0).any()
            or (off_np + len_np > T_in).any()):
        raise ValueError(
            f"sequence_slice: offset+length must stay inside the time "
            f"dim (T={T_in}); got offset={off_np.tolist()} "
            f"length={len_np.tolist()} (reference sequence_slice_op "
            "enforces the same)")
    ml = int(len_np.max()) if len_np.size else 0

    def f(dense):
        T = dense.shape[1]
        pos = jnp.arange(ml)[None, :] + jnp.asarray(off_np)[:, None]
        ok = jnp.arange(ml)[None, :] < jnp.asarray(len_np)[:, None]
        safe = jnp.clip(pos, 0, T - 1)
        idx = safe.reshape(safe.shape + (1,) * (dense.ndim - 2))
        out = jnp.take_along_axis(dense, idx, axis=1)
        okx = ok.reshape(ok.shape + (1,) * (dense.ndim - 2))
        return jnp.where(okx, out, 0)
    out = apply("sequence_slice", f, (_t(x),))
    return out, to_tensor(len_np)


def sequence_topk_avg_pooling(x, lengths, topks, name=None):
    """Average of the top-k valid timesteps per channel, for each k in
    ``topks`` (reference sequence_topk_avg_pooling_op, dense analog):
    x [B, T, C] → [B, len(topks)*C]. Rows shorter than k average their
    full valid prefix (the reference pads with the available values)."""
    ks = [int(k) for k in topks]

    def f(dense, lengths):
        B, T, C = dense.shape
        mask = jnp.arange(T)[None, :, None] < lengths[:, None, None]
        neg = jnp.finfo(dense.dtype).min
        masked = jnp.where(mask, dense, neg)
        srt = jnp.sort(masked, axis=1)[:, ::-1]       # desc over time
        outs = []
        for k in ks:
            kk = min(k, T)
            top = srt[:, :kk]
            cnt = jnp.minimum(lengths, kk)[:, None].astype(dense.dtype)
            valid = (jnp.arange(kk)[None, :, None]
                     < jnp.minimum(lengths, kk)[:, None, None])
            s = jnp.where(valid, top, 0).sum(axis=1)
            outs.append(s / jnp.maximum(cnt, 1))
        return jnp.concatenate(outs, axis=-1)
    return apply("sequence_topk_avg_pooling", f, (_t(x), _t(lengths)))
