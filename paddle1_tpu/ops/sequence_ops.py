"""Sequence ops over dense-plus-lengths ragged batches.

Analog of the reference's LoDTensor sequence op family
(/root/reference/paddle/fluid/operators/sequence_ops/, 6.2k LoC). The
LoD (level-of-detail offsets) representation is CPU-pointer-chasing by
design and hostile to XLA's static shapes; the TPU-native mapping (SURVEY
§7 hard part d) is a dense [batch, max_len, ...] tensor plus an int
``lengths`` vector — every op below is a masked dense computation that
jits cleanly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_expand",
           "sequence_reverse", "sequence_concat", "sequence_first_step",
           "sequence_last_step"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths → [batch, maxlen] 0/1 mask (reference sequence_mask_op)."""
    maxlen_static = maxlen

    def f(lengths):
        ml = maxlen_static if maxlen_static is not None else int(
            jnp.max(lengths))
        ids = jnp.arange(ml)[None, :]
        return (ids < lengths[:, None]).astype(jnp.dtype(dtype))
    return apply("sequence_mask", f, (_t(x),))


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """Flat packed rows [sum(len), ...] + lengths → dense
    [batch, maxlen, ...] (reference sequence_pad_op). Returns (padded,
    lengths)."""
    lengths_np = np.asarray(lengths.numpy() if isinstance(lengths, Tensor)
                            else lengths).astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(lengths_np)])
    ml = int(maxlen) if maxlen is not None else int(lengths_np.max())

    def f(flat, pv):
        rows = []
        for b, ln in enumerate(lengths_np):
            seg = flat[offsets[b]:offsets[b + 1]]
            pad_shape = (ml - int(ln),) + flat.shape[1:]
            pad = jnp.full(pad_shape, pv, flat.dtype)
            rows.append(jnp.concatenate([seg, pad], axis=0))
        return jnp.stack(rows)
    padded = apply("sequence_pad", f, (_t(x), _t(pad_value)))
    return padded, to_tensor(lengths_np)


def sequence_unpad(x, length, name=None):
    """Dense [batch, maxlen, ...] + lengths → flat packed rows
    (reference sequence_unpad_op)."""
    lengths_np = np.asarray(length.numpy() if isinstance(length, Tensor)
                            else length).astype(np.int64)

    def f(dense):
        segs = [dense[b, :int(ln)] for b, ln in enumerate(lengths_np)]
        return jnp.concatenate(segs, axis=0)
    return apply("sequence_unpad", f, (_t(x),))


def sequence_pool(x, lengths, pool_type="sum", name=None):
    """Masked pooling over the time dim (reference sequence_pool_op):
    sum/average/sqrt/max/first/last."""
    pool_type = pool_type.lower()

    def f(dense, lengths):
        ml = dense.shape[1]
        mask = (jnp.arange(ml)[None, :] < lengths[:, None])
        mexp = mask.reshape(mask.shape + (1,) * (dense.ndim - 2))
        if pool_type == "sum":
            return jnp.sum(jnp.where(mexp, dense, 0), axis=1)
        if pool_type in ("average", "mean"):
            s = jnp.sum(jnp.where(mexp, dense, 0), axis=1)
            return s / jnp.maximum(lengths, 1).astype(dense.dtype).reshape(
                (-1,) + (1,) * (dense.ndim - 2))
        if pool_type == "sqrt":
            s = jnp.sum(jnp.where(mexp, dense, 0), axis=1)
            return s / jnp.sqrt(jnp.maximum(lengths, 1).astype(
                dense.dtype)).reshape((-1,) + (1,) * (dense.ndim - 2))
        if pool_type == "max":
            neg = jnp.finfo(dense.dtype).min if jnp.issubdtype(
                dense.dtype, jnp.floating) else jnp.iinfo(dense.dtype).min
            return jnp.max(jnp.where(mexp, dense, neg), axis=1)
        if pool_type == "first":
            return dense[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(lengths - 1, 0)
            return jnp.take_along_axis(
                dense, idx.reshape((-1, 1) + (1,) * (dense.ndim - 2)),
                axis=1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")
    return apply("sequence_pool", f, (_t(x), _t(lengths)))


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over time (reference sequence_softmax_op)."""

    def f(dense, lengths):
        ml = dense.shape[1]
        mask = (jnp.arange(ml)[None, :] < lengths[:, None])
        mexp = mask.reshape(mask.shape + (1,) * (dense.ndim - 2))
        neg = jnp.finfo(dense.dtype).min
        masked = jnp.where(mexp, dense, neg)
        out = jax.nn.softmax(masked, axis=1)
        return jnp.where(mexp, out, 0)
    return apply("sequence_softmax", f, (_t(x), _t(lengths)))


def sequence_expand(x, lengths, name=None):
    """Repeat row b of x lengths[b] times along a new packed dim
    (reference sequence_expand_op dense analog)."""
    lengths_np = np.asarray(lengths.numpy() if isinstance(lengths, Tensor)
                            else lengths).astype(np.int64)

    def f(dense):
        return jnp.repeat(dense, jnp.asarray(lengths_np), axis=0,
                          total_repeat_length=int(lengths_np.sum()))
    return apply("sequence_expand", f, (_t(x),))


def sequence_reverse(x, lengths, name=None):
    """Reverse each row's valid prefix, keeping padding in place
    (reference sequence_reverse_op)."""

    def f(dense, lengths):
        ml = dense.shape[1]
        ids = jnp.arange(ml)[None, :]
        rev = lengths[:, None] - 1 - ids
        idx = jnp.where(ids < lengths[:, None], rev, ids)
        return jnp.take_along_axis(
            dense, idx.reshape(idx.shape + (1,) * (dense.ndim - 2)),
            axis=1)
    return apply("sequence_reverse", f, (_t(x), _t(lengths)))


def sequence_concat(xs, lengths_list, name=None):
    """Interleave several packed sequences batch-row-wise (reference
    sequence_concat_op): row b of the result is the concatenation of row b
    from each input. Returns (packed, lengths)."""
    ls = [np.asarray(l.numpy() if isinstance(l, Tensor) else l, np.int64)
          for l in lengths_list]
    offs = [np.concatenate([[0], np.cumsum(l)]) for l in ls]
    batch = len(ls[0])

    def f(*flats):
        rows = []
        for b in range(batch):
            for flat, off, l in zip(flats, offs, ls):
                rows.append(flat[off[b]:off[b] + int(l[b])])
        return jnp.concatenate(rows, axis=0)
    packed = apply("sequence_concat", f, tuple(_t(x) for x in xs))
    return packed, to_tensor(np.sum(ls, axis=0))
