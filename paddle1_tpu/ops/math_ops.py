"""Eager op surface: math / reduction / comparison ops.

TPU-native analog of the reference operator library's user-visible math ops
(/root/reference/paddle/fluid/operators/elementwise/, reduce_ops/,
activation_op.cc, matmul_v2_op.cc, ...) and the Python wrappers in
python/paddle/tensor/math.py. Each op is one pure jnp function routed through
``autograd.engine.apply``, which supplies the backward rule via jax.vjp — the
554-op C++ registry with hand-written grad kernels collapses into this table.
"""

from __future__ import annotations

import math as _pymath
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor
from ..core.errors import InvalidArgumentError

__all__ = []  # populated at bottom


def _t(x) -> Tensor:
    return x if isinstance(x, Tensor) else to_tensor(x)


def _unary(opname, jfn):
    def op(x, name=None):
        return apply(opname, jfn, (_t(x),))
    op.__name__ = opname
    return op


def _binary(opname, jfn):
    def op(x, y, name=None):
        if isinstance(y, Tensor) and not isinstance(x, Tensor):
            x = to_tensor(x, dtype=y.dtype)
        x = _t(x)
        return apply(opname, jfn, (x, y))
    op.__name__ = opname
    return op


# -- elementwise binary -------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
ldexp = _binary("ldexp", lambda x, y: x * (2.0 ** y))
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
logaddexp = _binary("logaddexp", jnp.logaddexp)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", lambda x, y: jnp.outer(x, y))
kron = _binary("kron", jnp.kron)
cross = _binary("cross", jnp.cross)
dot = _binary("dot", lambda x, y: (x * y).sum(-1) if x.ndim > 1 else jnp.dot(x, y))
mv = _binary("mv", jnp.matmul)

# -- elementwise unary --------------------------------------------------------
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sign = _unary("sign", jnp.sign)
reciprocal = _unary("reciprocal", jnp.reciprocal)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
i0 = _unary("i0", jax.scipy.special.i0)
i1 = _unary("i1", jax.scipy.special.i1)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isfinite = _unary("isfinite", jnp.isfinite)
isinf = _unary("isinf", jnp.isinf)
isnan = _unary("isnan", jnp.isnan)


def logit(x, eps=None, name=None):
    def f(x):
        xx = jnp.clip(x, eps, 1 - eps) if eps else x
        return jnp.log(xx / (1 - xx))
    return apply("logit", f, (_t(x),))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a),
                     (_t(x), _t(y), weight))
    return apply("lerp", lambda a, b: a + weight * (b - a), (_t(x), _t(y)))


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply("clip", lambda x: jnp.clip(x, lo, hi), (_t(x),))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(x):
        y = x * scale + bias if bias_after_scale else (x + bias) * scale
        return y
    out = apply("scale", f, (_t(x),))
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda x: scale_b * jnp.tanh(scale_a * x), (_t(x),))


def multiplex(inputs, index, name=None):
    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))),
            axis=0)[0]
    return apply("multiplex", f, (_t(index).astype("int32"),
                                  *[_t(x) for x in inputs]))


# -- matmul family ------------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply("matmul", f, (_t(x), _t(y)))


def mm(input, mat2, name=None):
    """Non-broadcasting matmul (reference tensor/math.py mm). Unlike
    matmul, batch dims must match exactly and inner dims must agree —
    ported code uses mm as a shape assertion."""
    a, b = _t(input), _t(mat2)
    if a.ndim < 1 or b.ndim < 1:
        raise InvalidArgumentError("mm: inputs must have ndim >= 1")
    ka = a.shape[-1]
    kb = b.shape[-2] if b.ndim >= 2 else b.shape[-1]
    if ka != kb or tuple(a.shape[:-2]) != tuple(b.shape[:-2]):
        raise InvalidArgumentError(
            f"mm does not broadcast: got shapes {list(a.shape)} x "
            f"{list(b.shape)}; use matmul for broadcasting semantics")
    return matmul(a, b)


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, (_t(x), _t(y)))


def increment(x, value=1.0, name=None):
    """In-place scalar increment (reference increment op, used by
    counters in static loops)."""
    out = apply("increment", lambda a: a + jnp.asarray(value, a.dtype),
                (_t(x),))
    if isinstance(x, Tensor):
        x._replace_impl(out)
        return x
    return out


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def broadcast_shape(x_shape, y_shape):
    """Shape-only broadcast result (reference tensor/manipulation.py
    broadcast_shape)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def tanh_(x, name=None):
    # single in-place implementation lives in nn.functional.activation
    from ..nn.functional.activation import tanh_ as _impl
    return _impl(x, name=name)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm",
                 lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                 (_t(input), _t(x), _t(y)))


def einsum(equation, *operands):
    return apply("einsum", lambda *xs: jnp.einsum(equation, *xs),
                 tuple(_t(o) for o in operands))


def matmul_int8(x, y, name=None):  # quantized matmul entry point
    return apply("matmul_int8",
                 lambda a, b: jax.lax.dot_general(
                     a, b, (((a.ndim - 1,), (0,)), ((), ())),
                     preferred_element_type=jnp.int32),
                 (_t(x), _t(y)))


# -- reductions ---------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def _reduce(opname, jfn, dtype_cast=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)

        def f(x):
            y = jfn(x, axis=ax, keepdims=keepdim)
            if dtype is not None:
                y = y.astype(dtypes.convert_dtype(dtype))
            return y
        return apply(opname, f, (_t(x),))
    op.__name__ = opname
    return op


sum = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)
min = _reduce("min", jnp.min)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
all = _reduce("all", jnp.all)
any = _reduce("any", jnp.any)
logsumexp = _reduce("logsumexp",
                    lambda x, axis, keepdims:
                    jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply("std", lambda x: jnp.std(x, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), (_t(x),))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply("var", lambda x: jnp.var(x, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), (_t(x),))


def median(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply("median",
                 lambda x: jnp.median(x, axis=ax, keepdims=keepdim), (_t(x),))


def quantile(x, q, axis=None, keepdim=False):
    ax = _norm_axis(axis)
    return apply("quantile",
                 lambda x: jnp.quantile(x, jnp.asarray(q), axis=ax,
                                        keepdims=keepdim), (_t(x),))


def cumsum(x, axis=None, dtype=None, name=None):
    def f(x):
        y = jnp.cumsum(x.reshape(-1) if axis is None else x,
                       axis=0 if axis is None else axis)
        return y.astype(dtypes.convert_dtype(dtype)) if dtype else y
    return apply("cumsum", f, (_t(x),))


def cumprod(x, dim=None, dtype=None, name=None):
    def f(x):
        y = jnp.cumprod(x.reshape(-1) if dim is None else x,
                        axis=0 if dim is None else dim)
        return y.astype(dtypes.convert_dtype(dtype)) if dtype else y
    return apply("cumprod", f, (_t(x),))


def cummax(x, axis=None, dtype="int64", name=None):
    ax = 0 if axis is None else axis
    xt = _t(x) if axis is not None else reshape(_t(x), [-1])
    v = apply("cummax", lambda x: jax.lax.associative_scan(
        jnp.maximum, x, axis=ax), (xt,))
    return v


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def f(*xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out
    return apply("add_n", f, tuple(_t(x) for x in inputs))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda x: jnp.trace(x, offset=offset, axis1=axis1,
                                              axis2=axis2), (_t(x),))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal",
                 lambda x: jnp.diagonal(x, offset=offset, axis1=axis1,
                                        axis2=axis2), (_t(x),))


# -- comparison / logical -----------------------------------------------------

equal = _binary("equal", lambda x, y: jnp.equal(x, y))
not_equal = _binary("not_equal", jnp.not_equal)
greater_than = _binary("greater_than", jnp.greater)
greater_equal = _binary("greater_equal", jnp.greater_equal)
less_than = _binary("less_than", jnp.less)
less_equal = _binary("less_equal", jnp.less_equal)
logical_and = _binary("logical_and", jnp.logical_and)
logical_or = _binary("logical_or", jnp.logical_or)
logical_xor = _binary("logical_xor", jnp.logical_xor)
logical_not = _unary("logical_not", jnp.logical_not)
bitwise_and = _binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not)


def equal_all(x, y, name=None):
    return apply("equal_all", lambda x, y: jnp.array_equal(x, y),
                 (_t(x), _t(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose",
                 lambda x, y: jnp.allclose(x, y, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 (_t(x), _t(y)))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose",
                 lambda x, y: jnp.isclose(x, y, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 (_t(x), _t(y)))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where", lambda c, x, y: jnp.where(c, x, y),
                 (_t(condition), _t(x), _t(y)))


def nonzero(x, as_tuple=False):
    # Dynamic output shape: eager-only (document as such, like the
    # reference's LoD-producing ops which were CPU-bound too).
    arr = np.asarray(_t(x).numpy())
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(to_tensor(i.astype(np.int64)) for i in idx)
    return to_tensor(np.stack(idx, axis=1).astype(np.int64))


# -- search / sort ------------------------------------------------------------

def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(x):
        y = jnp.argmax(x.reshape(-1) if axis is None else x,
                       axis=None if axis is None else axis,
                       keepdims=keepdim if axis is not None else False)
        return y.astype(dtypes.convert_dtype(dtype))
    return apply("argmax", f, (_t(x),))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(x):
        y = jnp.argmin(x.reshape(-1) if axis is None else x,
                       axis=None if axis is None else axis,
                       keepdims=keepdim if axis is not None else False)
        return y.astype(dtypes.convert_dtype(dtype))
    return apply("argmin", f, (_t(x),))


def argsort(x, axis=-1, descending=False, name=None):
    def f(x):
        idx = jnp.argsort(x, axis=axis, descending=descending)
        return idx.astype(jnp.int64)
    return apply("argsort", f, (_t(x),))


def sort(x, axis=-1, descending=False, name=None):
    return apply("sort",
                 lambda x: jnp.sort(x, axis=axis, descending=descending),
                 (_t(x),))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(x):
        xs = jnp.moveaxis(x, axis, -1)
        if largest:
            v, i = jax.lax.top_k(xs, k)
        else:
            v, i = jax.lax.top_k(-xs, k)
            v = -v
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis).astype(jnp.int64)
    return apply("topk", f, (_t(x),), n_outputs=2)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply("searchsorted", f, (_t(sorted_sequence), _t(values)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    arr = _t(x).numpy()
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return to_tensor(res)
    outs = [to_tensor(res[0])]
    for extra in res[1:]:
        outs.append(to_tensor(extra.astype(np.int64)))
    return tuple(outs)


def bincount(x, weights=None, minlength=0, name=None):
    arr = _t(x).numpy()
    w = weights.numpy() if isinstance(weights, Tensor) else weights
    return to_tensor(np.bincount(arr, weights=w, minlength=minlength))


def histogram(input, bins=100, min=0, max=0, name=None):
    arr = _t(input).numpy()
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return to_tensor(hist.astype(np.int64))


def masked_select(x, mask, name=None):
    arr = _t(x).numpy()
    m = _t(mask).numpy().astype(bool)
    return to_tensor(arr[m])


def index_sample(x, index):
    return apply("index_sample",
                 lambda x, i: jnp.take_along_axis(x, i, axis=1),
                 (_t(x), _t(index)))


def index_select(x, index, axis=0, name=None):
    return apply("index_select",
                 lambda x, i: jnp.take(x, i, axis=axis), (_t(x), _t(index)))


def mode(x, axis=-1, keepdim=False, name=None):
    def f(x):
        xs = jnp.sort(jnp.moveaxis(x, axis, -1), axis=-1)
        n = xs.shape[-1]
        eq = (xs[..., None, :] == xs[..., :, None]).sum(-1)
        best = jnp.argmax(eq, axis=-1)
        vals = jnp.take_along_axis(xs, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(jnp.moveaxis(x, axis, -1) == vals[..., None], axis=-1)
        if keepdim:
            vals, idx = vals[..., None], idx[..., None]
            return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)
        return vals, idx.astype(jnp.int64)
    return apply("mode", f, (_t(x),), n_outputs=2)


# -- reexport helpers used above ---------------------------------------------
from .manip_ops import reshape  # noqa: E402  (circular-safe: late import)

__all__ = [k for k, v in list(globals().items())
           if callable(v) and not k.startswith("_") and
           getattr(v, "__module__", "").endswith(("math_ops",))]
__all__ += ["matmul", "einsum", "where", "clip", "topk", "sort", "argsort"]
__all__ = sorted(set(__all__) - {"Tensor", "to_tensor", "apply", "reshape"})
