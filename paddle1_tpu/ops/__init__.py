"""Eager op library (the reference's operators/ + python/paddle/tensor analog).

Importing this package installs Tensor method/operator patches.
"""

from .math_ops import *  # noqa: F401,F403
from .manip_ops import *  # noqa: F401,F403
from . import linalg_ops as linalg
from .linalg_ops import (cholesky, det, dist, eig, eigh, inv, inverse,
                         lstsq, lu, matrix_power, matrix_rank, multi_dot,
                         norm, pinv, qr, slogdet, solve, svd,
                         triangular_solve)
from . import sequence_ops
from .sequence_ops import *  # noqa: F401,F403
from . import patch as _patch  # noqa: F401  (installs Tensor methods)
