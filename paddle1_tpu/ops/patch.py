"""Install operator overloads + tensor methods on Tensor.

Analog of the reference's monkey-patching of VarBase
(/root/reference/python/paddle/fluid/dygraph/math_op_patch.py and
varbase_patch_methods.py): the op library attaches itself to the tensor type
so the two stay decoupled.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor
from ..core import dtype as dtypes
from . import math_ops as M
from . import manip_ops as P


def _coerce_other(self, other):
    if isinstance(other, Tensor):
        return other
    return other  # scalars stay static attrs inside the jnp fn


def _install():
    T = Tensor

    # -- arithmetic operators -------------------------------------------
    T.__add__ = lambda s, o: M.add(s, o)
    T.__radd__ = lambda s, o: M.add(s, o)
    T.__sub__ = lambda s, o: M.subtract(s, o)
    T.__rsub__ = lambda s, o: M.subtract(to_tensor(o, dtype=s.dtype)
                                         if not isinstance(o, Tensor) else o, s)
    T.__mul__ = lambda s, o: M.multiply(s, o)
    T.__rmul__ = lambda s, o: M.multiply(s, o)
    T.__truediv__ = lambda s, o: M.divide(s, o)
    T.__rtruediv__ = lambda s, o: M.divide(
        to_tensor(o, dtype=s.dtype) if not isinstance(o, Tensor) else o, s)
    T.__floordiv__ = lambda s, o: M.floor_divide(s, o)
    T.__mod__ = lambda s, o: M.remainder(s, o)
    T.__pow__ = lambda s, o: M.pow(s, o)
    T.__rpow__ = lambda s, o: M.pow(
        to_tensor(o, dtype=s.dtype) if not isinstance(o, Tensor) else o, s)
    T.__neg__ = lambda s: M.neg(s)
    T.__abs__ = lambda s: M.abs(s)
    T.__matmul__ = lambda s, o: M.matmul(s, o)
    T.__rmatmul__ = lambda s, o: M.matmul(o, s)
    T.__invert__ = lambda s: M.logical_not(s) if s.dtype == dtypes.bool_ \
        else M.bitwise_not(s)
    T.__and__ = lambda s, o: M.logical_and(s, o) if s.dtype == dtypes.bool_ \
        else M.bitwise_and(s, o)
    T.__or__ = lambda s, o: M.logical_or(s, o) if s.dtype == dtypes.bool_ \
        else M.bitwise_or(s, o)
    T.__xor__ = lambda s, o: M.logical_xor(s, o) if s.dtype == dtypes.bool_ \
        else M.bitwise_xor(s, o)

    # comparisons return Tensors (like paddle), except __eq__ keeps Tensor
    # semantics for `in` / dict use via identity hash (already defined).
    T.__eq__ = lambda s, o: M.equal(s, o)
    T.__ne__ = lambda s, o: M.not_equal(s, o)
    T.__lt__ = lambda s, o: M.less_than(s, o)
    T.__le__ = lambda s, o: M.less_equal(s, o)
    T.__gt__ = lambda s, o: M.greater_than(s, o)
    T.__ge__ = lambda s, o: M.greater_equal(s, o)

    # -- indexing -------------------------------------------------------
    def _getitem(self, idx):
        idx = _unwrap_index(idx)
        return apply("getitem", lambda x: x[idx], (self,))

    def _setitem(self, idx, value):
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            out = apply("setitem",
                        lambda x, v: x.at[idx].set(v.astype(x.dtype)),
                        (self, value))
        else:
            out = apply("setitem", lambda x: x.at[idx].set(value), (self,))
        self._replace_impl(out)

    def _unwrap_index(idx):
        if isinstance(idx, Tensor):
            return idx.data
        if isinstance(idx, tuple):
            return tuple(i.data if isinstance(i, Tensor) else i for i in idx)
        if isinstance(idx, list):
            return jnp.asarray(idx)
        return idx

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # -- methods mirroring the functional API ---------------------------
    method_table = {}
    for mod in (M, P):
        for name in mod.__all__:
            fn = getattr(mod, name)
            if callable(fn):
                method_table.setdefault(name, fn)

    skip = {"zeros", "ones", "full", "empty", "arange", "linspace", "eye",
            "rand", "randn", "randint", "randperm", "meshgrid", "to_tensor",
            "uniform", "normal", "logspace", "shape"}
    for name, fn in method_table.items():
        if name in skip or hasattr(T, name):
            continue
        setattr(T, name, fn)

    # explicit methods whose names collide with attrs/builtins
    T.astype = lambda s, d: P.cast(s, d)
    T.cast = lambda s, d: P.cast(s, d)
    T.reshape = lambda s, *shape: P.reshape(
        s, shape[0] if len(shape) == 1 and isinstance(shape[0], (list, tuple))
        else list(shape))
    T.sum = lambda s, axis=None, keepdim=False, dtype=None, name=None: \
        M.sum(s, axis=axis, keepdim=keepdim, dtype=dtype)
    T.mean = lambda s, axis=None, keepdim=False, name=None: \
        M.mean(s, axis=axis, keepdim=keepdim)
    T.max = lambda s, axis=None, keepdim=False, name=None: \
        M.max(s, axis=axis, keepdim=keepdim)
    T.min = lambda s, axis=None, keepdim=False, name=None: \
        M.min(s, axis=axis, keepdim=keepdim)
    T.abs = lambda s: M.abs(s)
    T.pow = lambda s, o: M.pow(s, o)
    T.all = lambda s, axis=None, keepdim=False, name=None: \
        M.all(s, axis=axis, keepdim=keepdim)
    T.any = lambda s, axis=None, keepdim=False, name=None: \
        M.any(s, axis=axis, keepdim=keepdim)
    T.dim = lambda s: s.ndim
    T.numel_ = lambda s: s.size
    T.cpu = lambda s: s
    T.cuda = lambda s, *a, **k: s
    T.pin_memory = lambda s: s
    T.contiguous = lambda s: s
    T.is_contiguous = lambda s: True

    def _scale_(s, scale_v=1.0, bias=0.0, bias_after_scale=True):
        s._replace_impl(M.scale(s, scale_v, bias, bias_after_scale))
        return s
    T.scale_ = _scale_

    def _add_(s, o):
        s._replace_impl(M.add(s, o))
        return s
    T.add_ = _add_

    def _subtract_(s, o):
        s._replace_impl(M.subtract(s, o))
        return s
    T.subtract_ = _subtract_

    def _multiply_(s, o):
        s._replace_impl(M.multiply(s, o))
        return s
    T.multiply_ = _multiply_

    def _clip_(s, min=None, max=None):
        s._replace_impl(M.clip(s, min, max))
        return s
    T.clip_ = _clip_

    def _zero_(s):
        s._replace_impl(to_tensor(jnp.zeros_like(s.data)))
        return s
    T.zero_ = _zero_

    def _fill_(s, value):
        s._replace_impl(to_tensor(jnp.full_like(s.data, value)))
        return s
    T.fill_ = _fill_

    def _set_value(s, value):
        import numpy as np
        arr = value.data if isinstance(value, Tensor) else jnp.asarray(
            np.asarray(value), dtype=s.dtype)
        s._data = arr.astype(s.dtype)
        return s
    T.set_value = _set_value
    T.get_tensor = lambda s: s


_install()
