"""Functional (jax-transform) bridge for power users.

No direct reference analog; this is the TPU-native escape hatch: take a
Layer + loss closure and get back pure jax functions (value_and_grad over a
params pytree) for custom training loops, higher-order autodiff, or manual
pjit work.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax

from ..autograd import engine
from ..core.generator import rng_scope
from ..core.tensor import Tensor

__all__ = ["functional_call", "value_and_grad"]


def functional_call(layer, params: Dict[str, jax.Array], *args,
                    training: bool = False, rng_key=None):
    """Run ``layer.forward`` with ``params`` swapped in functionally.
    Traceable under jit/grad/vmap/shard_map."""
    key = rng_key if rng_key is not None else jax.random.key(0)
    was = layer.training
    layer.training = training
    try:
        with engine.no_grad(), rng_scope(key), \
                layer.load_functional_state(params):
            t_args = [Tensor(a, stop_gradient=True)
                      if not isinstance(a, Tensor) else a for a in args]
            out = layer.forward(*t_args)
            if isinstance(out, Tensor):
                return out.data
            if isinstance(out, (tuple, list)):
                return type(out)(o.data if isinstance(o, Tensor) else o
                                 for o in out)
            return out
    finally:
        layer.training = was


def value_and_grad(layer, loss_fn: Callable, has_aux: bool = False):
    """Build ``(params, batch, key) -> (loss, grads)`` for a Layer and a
    loss closure taking (outputs, batch)."""

    def compute(params, batch, key):
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        out = functional_call(layer, params, x, training=True, rng_key=key)
        return loss_fn(out, batch)

    return jax.value_and_grad(compute, has_aux=has_aux)
