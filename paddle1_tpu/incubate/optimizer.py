"""Optimizer wrappers: LookAhead, ModelAverage, ExponentialMovingAverage.

Analogs of the reference's
/root/reference/python/paddle/fluid/optimizer.py ExponentialMovingAverage
(:3311), ModelAverage (:3620) and LookaheadOptimizer (:5703). The
reference implements each as extra ops appended to the static program;
here they are eager wrappers over the parameter list — slot buffers live
beside the optimizer's, and ``apply()/restore()`` context-swap the
parameter data exactly like the reference's apply/restore programs.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..core.errors import InvalidArgumentError

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage"]


class ExponentialMovingAverage:
    """EMA of parameter values (reference optimizer.py:3311).

    ``update()`` after each optimizer step; ``apply()`` swaps EMA values
    in (bias-corrected, as the reference's decay-power correction does);
    ``restore()`` swaps the training values back.
    """

    def __init__(self, parameters, decay: float = 0.999, name=None):
        self._params = [p for p in parameters if not p.stop_gradient]
        self._decay = float(decay)
        self._ema: Dict[int, jnp.ndarray] = {
            id(p): jnp.zeros_like(p.data) for p in self._params}
        self._step = 0
        self._backup: Optional[Dict[int, jnp.ndarray]] = None

    def update(self) -> None:
        self._step += 1
        d = self._decay
        for p in self._params:
            self._ema[id(p)] = d * self._ema[id(p)] + (1.0 - d) * p.data

    def apply(self, need_restore: bool = True):
        """Swap EMA values into the parameters. Usable as a context
        manager (``with ema.apply(): evaluate()``) or imperatively."""
        if self._backup is not None:
            raise InvalidArgumentError("EMA already applied; restore first")
        if self._step == 0:
            raise InvalidArgumentError(
                "EMA.apply() before any update(): the moving averages are "
                "all zeros and would silently wipe the parameters")
        bc = 1.0 - self._decay ** self._step  # bias correction
        self._backup = {id(p): p.data for p in self._params}
        for p in self._params:
            p._data = (self._ema[id(p)] / bc).astype(p.data.dtype)
        ema = self

        class _Ctx:
            def __enter__(self):
                return ema

            def __exit__(self, *exc):
                if need_restore:
                    ema.restore()
                return False
        return _Ctx()

    def restore(self) -> None:
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    def state_dict(self) -> dict:
        return {"step": self._step, "decay": self._decay,
                "ema": {i: np.asarray(v)
                        for i, v in enumerate(self._ema.values())}}


class ModelAverage:
    """Sliding-window average of parameter values (reference
    optimizer.py:3620 — accumulates sum_1/sum_2/sum_3 blocks over a
    window sized by ``average_window_rate``; apply()/restore() swap the
    averaged values in for evaluation)."""

    def __init__(self, average_window_rate: float, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        if parameters is None:
            raise InvalidArgumentError(
                "ModelAverage needs the parameter list in eager mode")
        self._params = [p for p in parameters if not p.stop_gradient]
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum: Dict[int, jnp.ndarray] = {
            id(p): jnp.zeros_like(p.data) for p in self._params}
        self._n = 0
        self._total_steps = 0
        self._backup: Optional[Dict[int, jnp.ndarray]] = None

    def update(self) -> None:
        """Accumulate after each step; restart the window when it outgrows
        max(min_average_window, total_steps * rate) (the reference's
        window-restart rule)."""
        self._total_steps += 1
        window = max(self.min_w, int(self._total_steps * self.rate))
        window = min(window, self.max_w)
        if self._n >= window:
            for p in self._params:
                self._sum[id(p)] = jnp.zeros_like(p.data)
            self._n = 0
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p.data
        self._n += 1

    def apply(self, executor=None, need_restore: bool = True):
        if self._n == 0:
            raise InvalidArgumentError("ModelAverage: no accumulated steps")
        if self._backup is not None:
            raise InvalidArgumentError("already applied; restore first")
        self._backup = {id(p): p.data for p in self._params}
        for p in self._params:
            p._data = (self._sum[id(p)] / self._n).astype(p.data.dtype)
        ma = self

        class _Ctx:
            def __enter__(self):
                return ma

            def __exit__(self, *exc):
                if need_restore:
                    ma.restore()
                return False
        return _Ctx()

    def restore(self, executor=None) -> None:
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    # optimizer-protocol passthroughs so hapi/training loops accept it
    def step(self):
        self.update()

    def clear_grad(self):
        pass


class LookAhead:
    """Lookahead optimizer (reference LookaheadOptimizer:5703; k fast
    steps with the inner optimizer, then slow weights catch up:
    slow += alpha * (fast - slow); fast = slow)."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        if inner_optimizer is None:
            raise InvalidArgumentError("inner optimizer cannot be None")
        if not 0.0 <= alpha <= 1.0:
            raise InvalidArgumentError("alpha must be in [0, 1]")
        if k < 1:
            raise InvalidArgumentError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        params = inner_optimizer._parameter_list or []
        self._slow: Dict[int, jnp.ndarray] = {
            id(p): p.data for p in params}
        self._params = list(params)

    def step(self) -> None:
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            a = self.alpha
            for p in self._params:
                slow = self._slow[id(p)]
                slow = slow + a * (p.data - slow)
                self._slow[id(p)] = slow
                p._data = slow.astype(p.data.dtype)

    minimize_step = step

    def clear_grad(self) -> None:
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self) -> dict:
        return {"inner": self.inner_optimizer.state_dict(),
                "step": self._step_count,
                "slow": {str(i): np.asarray(v)
                         for i, v in enumerate(self._slow.values())}}

    def set_state_dict(self, state: dict) -> None:
        # without this, __getattr__ would hand the wrong-shaped dict to
        # the inner optimizer and silently drop its moments on resume
        self.inner_optimizer.set_state_dict(state["inner"])
        self._step_count = int(state.get("step", 0))
        slow = state.get("slow", {})
        for i, p in enumerate(self._params):
            v = slow.get(str(i), slow.get(i))
            if v is not None:
                self._slow[id(p)] = jnp.asarray(v)

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)
