"""Auto-checkpoint / elastic resume.

Analog of the reference's auto-checkpoint
(python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71
train_epoch_range, :598) and the hapi ModelCheckpoint: wrap the epoch loop;
each epoch end snapshots registered state (model + optimizer + RNG + epoch
counter) atomically to the checkpoint dir; on restart the loop resumes at
the saved epoch. The reference keyed snapshots on a program hash and wrote
to HDFS — here the key is a user name/hash and the sink is a directory
(works for local disk or a mounted DFS).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Iterator, Optional

__all__ = ["train_epoch_range", "ExeTrainStatus"]

_CKPT_ENV = "PADDLE_CHECKPOINT_DIR"


class ExeTrainStatus:
    """Resume bookkeeping (reference auto_checkpoint.py ExeTrainStatus)."""

    def __init__(self, name: str, max_epoch: int, save_dir: str,
                 fs=None, remote_dir: Optional[str] = None):
        self.name = name
        self.max_epoch = max_epoch
        self.save_dir = save_dir
        # remote sink (reference writes snapshots to HDFS through the fs
        # abstraction — fleet/utils/fs.py); local publish stays atomic and
        # the remote copy follows
        self.fs = fs
        self.remote_dir = remote_dir
        self._layers = []
        self._optimizers = []
        self.epoch = -1
        self._last_saved: Optional[str] = None

    # -- registration -------------------------------------------------------

    def register(self, *objs):
        """Register Layers/Optimizers whose state belongs in the snapshot."""
        for o in objs:
            if hasattr(o, "state_dict") and hasattr(o, "set_state_dict"):
                if hasattr(o, "parameters") and not hasattr(o, "_update"):
                    self._layers.append(o)
                else:
                    self._optimizers.append(o)
        return self

    # -- snapshot I/O -------------------------------------------------------

    def _meta_path(self):
        return os.path.join(self.save_dir, f"{self.name}.meta.json")

    def _state_path(self, epoch):
        return os.path.join(self.save_dir, f"{self.name}.e{epoch}.pdckpt")

    def save(self, epoch: int):
        from ..framework.io import save as fsave
        from ..core.generator import get_rng_state
        os.makedirs(self.save_dir, exist_ok=True)
        state = {
            "layers": [l.state_dict() for l in self._layers],
            "optimizers": [o.state_dict() for o in self._optimizers],
            "rng": get_rng_state(),
            "epoch": epoch,
        }
        path = self._state_path(epoch)
        tmp = path + f".tmp{os.getpid()}"
        fsave(state, tmp)
        os.replace(tmp, path)                      # atomic publish
        meta = {"epoch": epoch, "path": path, "ts": time.time(),
                "name": self.name, "max_epoch": self.max_epoch}
        mtmp = self._meta_path() + f".tmp{os.getpid()}"
        with open(mtmp, "w") as f:
            json.dump(meta, f)
        os.replace(mtmp, self._meta_path())
        # keep only the latest snapshot (reference keeps max_no = 3 on fs)
        if self._last_saved and self._last_saved != path and \
                os.path.exists(self._last_saved):
            os.remove(self._last_saved)
        self._last_saved = path
        if self.fs is not None and self.remote_dir:
            self.fs.mkdirs(self.remote_dir)
            for local in (path, self._meta_path()):
                dst = os.path.join(self.remote_dir,
                                   os.path.basename(local))
                if self.fs.is_exist(dst):
                    self.fs.delete(dst)
                self.fs.upload(local, dst)

    def try_restore(self) -> int:
        """Returns the next epoch to run (0 if no snapshot)."""
        from ..framework.io import load as fload
        if not os.path.exists(self._meta_path()) and self.fs is not None \
                and self.remote_dir:
            # cold host: pull the latest snapshot from the remote sink.
            # The meta file is published LAST (os.replace after the state
            # file lands) so a failed state download leaves no local meta
            # and the pull retries on the next start.
            rmeta = os.path.join(self.remote_dir,
                                 os.path.basename(self._meta_path()))
            if self.fs.is_exist(rmeta):
                os.makedirs(self.save_dir, exist_ok=True)
                mtmp = self._meta_path() + f".dl{os.getpid()}"
                self.fs.download(rmeta, mtmp)
                with open(mtmp) as f:
                    remote_state = os.path.basename(json.load(f)["path"])
                self.fs.download(
                    os.path.join(self.remote_dir, remote_state),
                    os.path.join(self.save_dir, remote_state))
                os.replace(mtmp, self._meta_path())
        if not os.path.exists(self._meta_path()):
            return 0
        with open(self._meta_path()) as f:
            meta = json.load(f)
        path = meta.get("path")
        if path and not os.path.exists(path):
            # the snapshot may come from a host with a DIFFERENT save_dir
            # (remote restore): resolve by basename in our own dir
            local = os.path.join(self.save_dir, os.path.basename(path))
            path = local if os.path.exists(local) else path
        if not path or not os.path.exists(path):
            return 0
        state = fload(path)
        for l, sd in zip(self._layers, state["layers"]):
            l.set_state_dict(sd)
        for o, sd in zip(self._optimizers, state["optimizers"]):
            o.set_state_dict(sd)
        try:
            from ..core.generator import set_rng_state
            set_rng_state(state["rng"])
        except Exception:
            pass
        self.epoch = state["epoch"]
        self._last_saved = path
        return self.epoch + 1


def train_epoch_range(max_epoch_num: int, *objs, name: str = "auto_ckpt",
                      save_checkpoint_inter: int = 1,
                      checkpoint_dir: Optional[str] = None,
                      fs=None, remote_dir: Optional[str] = None
                      ) -> Iterator[int]:
    """for epoch in train_epoch_range(N, model, opt): ...  (reference
    auto_checkpoint.py:71). Yields epoch indices, resuming after restart;
    snapshots every ``save_checkpoint_inter`` epochs when a checkpoint dir
    is configured (arg or $PADDLE_CHECKPOINT_DIR)."""
    ckpt_dir = checkpoint_dir or os.environ.get(_CKPT_ENV)
    if not ckpt_dir:
        yield from range(max_epoch_num)
        return
    status = ExeTrainStatus(name, max_epoch_num, ckpt_dir, fs=fs,
                            remote_dir=remote_dir).register(*objs)
    start = status.try_restore()
    for epoch in range(start, max_epoch_num):
        yield epoch
        if (epoch + 1) % save_checkpoint_inter == 0 or \
                epoch == max_epoch_num - 1:
            status.save(epoch)
