"""Incubating features (reference python/paddle/incubate +
fluid/incubate): auto-checkpoint, functional higher-order autodiff bridge.
"""

from . import auto_checkpoint
from . import functional
from .auto_checkpoint import train_epoch_range

__all__ = ["auto_checkpoint", "functional", "train_epoch_range"]
