"""Incubating features (reference python/paddle/incubate +
fluid/incubate): auto-checkpoint, functional higher-order autodiff bridge.
"""

from . import auto_checkpoint
from . import functional
from . import optimizer
from .auto_checkpoint import train_epoch_range
from .optimizer import (ExponentialMovingAverage, LookAhead, ModelAverage)

__all__ = ["auto_checkpoint", "functional", "optimizer",
           "train_epoch_range", "ExponentialMovingAverage", "LookAhead",
           "ModelAverage"]
