"""Incubating features (reference python/paddle/incubate +
fluid/incubate): auto-checkpoint, functional higher-order autodiff bridge.
"""

from . import functional
