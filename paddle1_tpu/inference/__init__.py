"""Inference deployment surface: Config + Predictor over the jit.save
artifact.

Analog of the reference's AnalysisConfig/AnalysisPredictor API
(/root/reference/paddle/fluid/inference/api/paddle_api.h:85-301,
paddle_analysis_config.h; Python bindings inference/api/api_impl.cc).

TPU-native inversion: the reference predictor owns an optimization
pipeline (IR passes, TensorRT subgraphs, memory reuse) applied to a
ProgramDesc at load time. Here the artifact IS the optimized program — a
serialized StableHLO executable produced by ``jit.save`` — and XLA
performs fusion/layout/memory optimization at (cached) compile time, so
most Config toggles are accepted for API parity and recorded in
``summary()`` rather than steering passes. Device choice selects the jax
backend. The C deployment path (reference inference/capi/) is
``core/native/src/capi.cc`` — a plain C ABI over this module via an
embedded interpreter.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "PredictorHandle", "create_predictor",
           "PrecisionType", "PlaceType", "get_version"]


class PrecisionType:
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kTPU = 2
    kXPU = 3


def get_version() -> str:
    from .. import __version__
    return __version__


class Config:
    """Predictor configuration (reference AnalysisConfig).

    Accepts either ``Config(model_dir)`` (directory containing
    ``__model__``-style pair) or ``Config(prog_file, params_file)`` where
    ``prog_file`` is the ``<path>.pdmodel`` written by ``jit.save``.
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._prog_file = None
        self._params_file = None
        if prog_file is not None and params_file is None and \
                os.path.isdir(prog_file):
            self.set_model(prog_file)
        elif prog_file is not None:
            self.set_model(prog_file, params_file)
        self._device = "auto"      # auto → tpu if present else cpu
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._ir_optim = True      # XLA always optimizes; recorded only
        self._profile = False
        self._glog_info = True
        self._cpu_math_threads = 1
        self._extra: Dict[str, object] = {}

    # -- model location ----------------------------------------------------

    def set_model(self, prog_or_dir: str,
                  params_file: Optional[str] = None) -> None:
        if params_file is None and os.path.isdir(prog_or_dir):
            # directory form: find a single *.pdmodel inside
            cands = [f for f in os.listdir(prog_or_dir)
                     if f.endswith(".pdmodel")]
            if len(cands) != 1:
                raise ValueError(
                    f"Config(model_dir): expected exactly one .pdmodel in "
                    f"{prog_or_dir}, found {cands}")
            base = os.path.join(prog_or_dir, cands[0][:-len(".pdmodel")])
            self._prog_file = base + ".pdmodel"
            self._params_file = base + ".pdiparams"
        else:
            self._prog_file = prog_or_dir
            self._params_file = params_file
        if self._prog_file and not self._prog_file.endswith(".pdmodel"):
            self._prog_file += ".pdmodel"
        if self._params_file is None and self._prog_file:
            self._params_file = self._prog_file[:-len(".pdmodel")] + \
                ".pdiparams"

    def model_program_path(self) -> Optional[str]:
        return self._prog_file

    def params_file_path(self) -> Optional[str]:
        return self._params_file

    # -- device ------------------------------------------------------------

    def enable_tpu(self, device_id: int = 0) -> None:
        self._device, self._device_id = "tpu", device_id

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0) -> None:
        # GPU request maps to the accelerator backend (TPU) if present —
        # the artifact is device-agnostic StableHLO
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self) -> None:
        self._device = "cpu"

    def use_gpu(self) -> bool:
        return self._device == "tpu"

    def gpu_device_id(self) -> int:
        return self._device_id

    def set_cpu_math_library_num_threads(self, n: int) -> None:
        self._cpu_math_threads = int(n)

    def cpu_math_library_num_threads(self) -> int:
        return self._cpu_math_threads

    # -- precision ---------------------------------------------------------

    def enable_quantized_inference(self,
                                   precision: int = PrecisionType.Int8
                                   ) -> None:
        """Weight-only quantized execution (the TPU-native stand-in for
        the reference's MKLDNN/TRT int8 passes, mkldnn_quantizer.cc):
        float parameters are stored as int8 + per-tensor scales and
        dequantized IN-GRAPH to bfloat16 in front of the exported
        program — 4x weight memory, XLA fuses the dequant into the
        first consumer. Activations stay bf16 (weight-only int8 is the
        TPU-idiomatic quantized-serving mode)."""
        if precision not in (PrecisionType.Int8, PrecisionType.Bfloat16):
            raise ValueError("quantized inference supports Int8 "
                             "(weight-only) or Bfloat16")
        self._precision = precision

    def precision_mode(self) -> int:
        return self._precision

    # -- optimization toggles (parity; XLA owns the pipeline) --------------

    def switch_ir_optim(self, flag: bool = True) -> None:
        self._ir_optim = bool(flag)

    def ir_optim(self) -> bool:
        return self._ir_optim

    def enable_memory_optim(self, flag: bool = True) -> None:
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self) -> bool:
        return self._memory_optim

    def enable_profile(self) -> None:
        self._profile = True

    def disable_glog_info(self) -> None:
        self._glog_info = False

    def set_optim_cache_dir(self, d: str) -> None:
        self._extra["optim_cache_dir"] = d

    def set_cipher_key(self, key: bytes) -> None:
        """Deploy encrypted artifacts (reference paddle_crypto +
        AnalysisConfig::SetModelBuffer): the Predictor decrypts
        .pdmodel/.pdiparams written by framework.crypto.Cipher."""
        self._extra["cipher_key"] = key

    def switch_use_feed_fetch_ops(self, flag: bool = False) -> None:
        self._extra["use_feed_fetch_ops"] = bool(flag)

    def switch_specify_input_names(self, flag: bool = True) -> None:
        self._extra["specify_input_names"] = bool(flag)

    def summary(self) -> str:
        rows = [("model file", self._prog_file),
                ("params file", self._params_file),
                ("device", f"{self._device}:{self._device_id}"),
                ("precision", self._precision),
                ("ir optim (XLA)", self._ir_optim),
                ("memory optim (XLA)", self._memory_optim),
                ("profile", self._profile)]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k.ljust(w)} : {v}" for k, v in rows)


class PredictorHandle:
    """Input/output tensor handle (reference ZeroCopyTensor,
    paddle_api.h:117): host-side staging buffer with copy_from_cpu /
    copy_to_cpu."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._dtype = dtype
        self._buf: Optional[np.ndarray] = None

    def reshape(self, shape: Sequence[int]) -> None:
        self._shape = list(shape)

    def copy_from_cpu(self, arr) -> None:
        self._buf = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._buf is None:
            from ..core.errors import PreconditionNotMetError
            if self._shape is not None:
                # reshape() was called but no data ever arrived — the
                # classic zero-copy-API stumble (reshape only declares
                # the expected shape; it allocates nothing)
                raise PreconditionNotMetError(
                    f"handle {self.name!r}: reshape({self._shape}) only "
                    "set the expected shape — it holds no data. For an "
                    "input handle call copy_from_cpu(array) after "
                    "reshape(); for an output handle call run() first.")
            raise PreconditionNotMetError(
                f"handle {self.name!r}: no data (run() first for "
                "outputs / copy_from_cpu for inputs)")
        return self._buf

    def shape(self) -> List[int]:
        if self._buf is not None:
            return list(self._buf.shape)
        return list(self._shape or [])

    def type(self):
        return self._buf.dtype if self._buf is not None else self._dtype


class Predictor:
    """Executable predictor over a jit.save artifact (reference
    AnalysisPredictor via CreatePaddlePredictor, analysis_predictor.cc).
    """

    def __init__(self, config: Config):
        self.config = config
        prog = config.model_program_path()
        if prog is None or not os.path.exists(prog):
            raise FileNotFoundError(f"model file not found: {prog}")
        base = prog[:-len(".pdmodel")]

        if config._device == "cpu":
            # pin the CPU backend BEFORE any jax import side effects
            import jax
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass

        key = config._extra.get("cipher_key")
        from ..framework.crypto import is_encrypted
        pfile = config.params_file_path()
        enc_prog, enc_params = is_encrypted(prog), is_encrypted(pfile)
        if (enc_prog or enc_params) and key is None:
            raise ValueError(
                "model artifact is encrypted; call Config.set_cipher_key()")
        if key is not None and (enc_prog or enc_params):
            # decrypt IN MEMORY (each file independently — either half may
            # be plaintext): no plaintext ever touches disk, matching the
            # reference's SetModelBuffer threat model
            import pickle
            from jax import export as jexport
            from ..framework.crypto import Cipher
            from ..framework.io import _unpack
            from ..jit import TranslatedLayer
            cipher = Cipher(key)
            with open(prog, "rb") as f:
                mbytes = f.read()
            if enc_prog:
                mbytes = cipher.decrypt(mbytes)
            with open(pfile, "rb") as f:
                pbytes = f.read()
            if enc_params:
                pbytes = cipher.decrypt(pbytes)
            # the sidecar is plaintext: run the same compat gate the
            # unencrypted jit.load path enforces
            from ..framework import op_version as _opv
            saved_compat = None
            try:
                with open(base + ".pdconfig") as f:
                    saved_compat = json.load(f).get("compat")
            except (OSError, ValueError):
                pass
            _opv.check_compat(saved_compat,
                              source=f"encrypted artifact {base!r}")
            exported = jexport.deserialize(mbytes)
            params = _unpack(pickle.loads(pbytes), return_numpy=True)
            self._layer = TranslatedLayer(exported, params)
        else:
            from ..jit import load as jit_load
            self._layer = jit_load(base)

        if config._precision in (PrecisionType.Int8,
                                 PrecisionType.Bfloat16):
            self._enable_weight_quantization(config._precision)

        meta_path = base + ".pdconfig"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            self._input_meta = meta.get("inputs", [])
            self._n_outputs = meta.get("n_outputs")
        else:
            self._input_meta = []
            self._n_outputs = None
        if not self._input_meta:
            # no sidecar (pre-sidecar artifact): the exported in_tree is
            # (params_dict, *inputs) flattened — subtract the param leaves
            # to recover the real input count
            try:
                total = self._layer._exported.in_tree.num_leaves
                n = max(1, total - len(self._layer._params_arrays))
            except Exception:
                n = 1
            self._input_meta = [{"name": f"input_{i}"} for i in range(n)]
        self._inputs = {m["name"]: PredictorHandle(
            m["name"], m.get("shape"), m.get("dtype"))
            for m in self._input_meta}
        self._outputs: Dict[str, PredictorHandle] = {}

    # -- reference surface --------------------------------------------------

    def get_input_names(self) -> List[str]:
        return [m["name"] for m in self._input_meta]

    def get_input_handle(self, name: str) -> PredictorHandle:
        if name not in self._inputs:
            from ..core.errors import NotFoundError
            raise NotFoundError(f"unknown input {name!r}; inputs: "
                                f"{self.get_input_names()}")
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        if self._outputs:
            return list(self._outputs)
        n = self._n_outputs or 1
        return [f"output_{i}" for i in range(n)]

    def get_output_handle(self, name: str) -> PredictorHandle:
        if name not in self._outputs:
            self._outputs[name] = PredictorHandle(name)
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute. Either positional ``inputs`` or pre-filled input
        handles (zero-copy style)."""
        if inputs is None:
            unfilled = [m["name"] for m in self._input_meta
                        if self._inputs[m["name"]]._buf is None]
            if unfilled:
                from ..core.errors import PreconditionNotMetError
                raise PreconditionNotMetError(
                    f"Predictor.run(): input handle(s) {unfilled} were "
                    "never filled — for each input, "
                    "get_input_handle(name).copy_from_cpu(array) before "
                    "run() (reshape() alone declares a shape, it does "
                    "not provide data), or pass run([arrays...]) "
                    "positionally.")
            inputs = [self._inputs[m["name"]].copy_to_cpu()
                      for m in self._input_meta]
        outs = self._layer(*inputs)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        arrs = [np.asarray(o.numpy() if hasattr(o, "numpy") else o)
                for o in outs]
        for i, a in enumerate(arrs):
            self.get_output_handle(f"output_{i}").copy_from_cpu(a)
        return arrs

    def serve(self, **kwargs) -> "object":
        """Wrap this predictor in a dynamic micro-batching
        :class:`~paddle1_tpu.serving.Server` (not started — call
        ``.start()``). Keyword args pass through (``max_batch``,
        ``batch_timeout_ms``, ``queue_depth``, ``buckets``,
        ``deadline_ms``, ``warmup=True`` pre-compiles every bucket from
        the artifact's input sidecar). The serving engine threads the
        loaded StableHLO program's params through jit — single-request
        ``run()`` and served responses match bit-for-bit."""
        from ..serving import Server
        return Server(self, **kwargs)

    def clear_intermediate_tensor(self) -> None:
        pass  # XLA owns buffers; parity no-op

    def try_shrink_memory(self) -> None:
        pass

    # -- weight-only quantized execution ------------------------------------

    def _enable_weight_quantization(self, precision: int) -> None:
        """Swap the loaded layer's forward for a jitted wrapper that
        holds float params as int8 (+ per-tensor absmax scales) or
        bfloat16 and dequantizes IN-GRAPH before calling the exported
        program (Config.enable_quantized_inference)."""
        import jax
        import jax.numpy as jnp
        layer = self._layer
        exported = layer._exported
        qparams: Dict[str, np.ndarray] = {}
        scales: Dict[str, np.ndarray] = {}
        for k, v in layer._params_arrays.items():
            v = np.asarray(v)
            # int8 only for matmul-class weights (ndim >= 2): 1-D
            # params (biases, LayerNorm scales) are a rounding error of
            # total bytes but outlier-sensitive — keep them float
            if np.issubdtype(v.dtype, np.floating) and v.ndim >= 2:
                if precision == PrecisionType.Int8:
                    s = np.maximum(np.abs(v).max(), 1e-8) / 127.0
                    qparams[k] = np.round(v / s).astype(np.int8)
                    scales[k] = np.float32(s)
                else:
                    qparams[k] = v.astype(jnp.bfloat16)
                    scales[k] = np.float32(1.0)
            else:
                qparams[k] = v
                scales[k] = np.float32(0.0)  # marker: pass-through

        def call(qp, sc, *inputs):
            full = {}
            for k, q in qp.items():
                s = sc[k]
                if q.dtype == jnp.int8:
                    full[k] = (q.astype(jnp.bfloat16) * s).astype(
                        jnp.float32)
                elif q.dtype == jnp.bfloat16:
                    full[k] = q.astype(jnp.float32)
                else:
                    full[k] = q
            return exported.call(full, *inputs)

        jitted = jax.jit(call)
        qp = {k: jnp.asarray(v) for k, v in qparams.items()}
        sc = {k: jnp.asarray(v) for k, v in scales.items()}

        class _QuantRunner:
            def __call__(self, *inputs):
                from ..core.tensor import Tensor, to_tensor
                arrs = [i.data if isinstance(i, Tensor) else
                        np.asarray(i) for i in inputs]
                out = jitted(qp, sc, *arrs)
                if isinstance(out, (list, tuple)):
                    return type(out)(to_tensor(o) for o in out)
                return to_tensor(out)

            _exported = exported
            _params_arrays = layer._params_arrays

        self._layer = _QuantRunner()


def create_predictor(config: Config) -> Predictor:
    """Reference paddle_infer::CreatePredictor."""
    return Predictor(config)
