"""Model encryption (reference paddle/fluid/framework/io/crypto/:
cipher.h CipherFactory / AESCipher over the inference artifacts, exposed
via paddle_crypto and AnalysisConfig::SetModelBuffer for encrypted
deployment).

AES-256-GCM via the ``cryptography`` package: authenticated encryption,
random 96-bit nonce per file, format ``b"P1CRYPT1" || nonce || ciphertext
(|| GCM tag)``. Keys are raw 32-byte secrets (hex-encodable with
:func:`CipherUtils.gen_key_to_file`).
"""

from __future__ import annotations

import os
from typing import Optional

from ..core.errors import InvalidArgumentError

__all__ = ["Cipher", "CipherFactory", "CipherUtils"]

_MAGIC = b"P1CRYPT1"


class Cipher:
    """AES-256-GCM cipher (reference AESCipher, crypto/aes_cipher.cc)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise InvalidArgumentError(
                f"cipher key must be 32 bytes (AES-256), got {len(key)}")
        self._key = key

    def encrypt(self, plaintext: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        nonce = os.urandom(12)
        ct = AESGCM(self._key).encrypt(nonce, plaintext, _MAGIC)
        return _MAGIC + nonce + ct

    def decrypt(self, blob: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        if not blob.startswith(_MAGIC):
            raise InvalidArgumentError(
                "not an encrypted paddle1_tpu artifact (bad magic)")
        nonce, ct = blob[len(_MAGIC):len(_MAGIC) + 12], \
            blob[len(_MAGIC) + 12:]
        try:
            return AESGCM(self._key).decrypt(nonce, ct, _MAGIC)
        except Exception as e:
            raise InvalidArgumentError(
                "decryption failed: wrong key or corrupted file") from e

    def encrypt_file(self, in_path: str, out_path: str) -> None:
        with open(in_path, "rb") as f:
            blob = self.encrypt(f.read())
        tmp = out_path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, out_path)

    def decrypt_file(self, in_path: str, out_path: str) -> None:
        with open(in_path, "rb") as f:
            plain = self.decrypt(f.read())
        tmp = out_path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(plain)
        os.replace(tmp, out_path)


class CipherFactory:
    """Reference CipherFactory::CreateCipher (config names an AES mode;
    GCM is the only mode here — CBC without auth is not worth carrying)."""

    @staticmethod
    def create_cipher(config_fpath: Optional[str] = None,
                      key: Optional[bytes] = None) -> Cipher:
        if key is None:
            raise InvalidArgumentError("create_cipher needs key=")
        return Cipher(key)


class CipherUtils:
    """Reference crypto/cipher_utils.cc helpers."""

    @staticmethod
    def gen_key(length: int = 32) -> bytes:
        return os.urandom(length)

    @staticmethod
    def gen_key_to_file(path: str, length: int = 32) -> bytes:
        k = CipherUtils.gen_key(length)
        # create with the final 0600 mode atomically — a write-then-chmod
        # leaves a umask-default-readable window on multi-user hosts
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            os.write(fd, k.hex().encode())
        finally:
            os.close(fd)
        return k

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return bytes.fromhex(f.read().decode().strip())


def is_encrypted(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            return f.read(len(_MAGIC)) == _MAGIC
    except OSError:
        return False
