"""Object checkpointing: paddle.save / paddle.load.

Analog of /root/reference/python/paddle/framework/io.py (save:494,
load:688): pickled nested containers of tensors. TPU-native format: tensors
are serialized as numpy arrays inside the pickle (bfloat16 via ml_dtypes
round-trips natively); everything else passes through pickle unchanged, so
``state_dict`` + optimizer state + arbitrary user objects all round-trip
exactly as in the reference.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPayload:
    """Pickle surrogate for Tensor; keeps dtype (incl. bfloat16) exactly."""

    def __init__(self, array: np.ndarray, is_parameter: bool, name,
                 stop_gradient: bool):
        self.array = array
        self.is_parameter = is_parameter
        self.name = name
        self.stop_gradient = stop_gradient

    def restore(self):
        if self.is_parameter:
            t = Parameter(self.array, name=self.name)
        else:
            t = Tensor(self.array, stop_gradient=self.stop_gradient,
                       name=self.name)
        return t


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.data),
                              isinstance(obj, Parameter), obj.name,
                              obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return type(obj)(packed) if not isinstance(obj, tuple) else tuple(packed)
    return obj


def _unpack(obj: Any, return_numpy: bool = False) -> Any:
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else obj.restore()
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs) -> None:
    """Save a nested object (state_dicts, tensors, python objects)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
