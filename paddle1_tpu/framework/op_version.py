"""Op-version / artifact-compatibility registry — the analog of the
reference's op version registry (/root/reference/paddle/fluid/framework/
op_version_registry.h: ops register semantic-change checkpoints;
serialized programs carry the versions they were built with and loaders
check compatibility).

Here the serialized artifact is ``jit.save``'s StableHLO + sidecar; the
XLA bytecode carries its own stability guarantees, so what needs
versioning is the FRAMEWORK-level semantics around it: the artifact
format (what files exist, how feeds/fetches are described) and the ops
whose *numerical contract* changed between rounds (the reference's
``ModifyAttr``/``NewInput`` checkpoint kinds collapse to a note string
per bump).

Surface:
* :func:`register_op_version` — record a semantic-change checkpoint.
* :func:`snapshot` — what ``jit.save`` embeds in the sidecar.
* :func:`check_compat` — what ``jit.load``/the Predictor run against a
  loaded sidecar: artifacts from a NEWER runtime refuse to load
  (the reference's IsMatched failure); artifacts from an OLDER runtime
  load with a warning listing the semantic changes in between.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Tuple

from ..core.errors import UnimplementedError

__all__ = ["FORMAT_VERSION", "register_op_version", "op_version",
           "snapshot", "check_compat", "OpVersionError"]

# Artifact FORMAT version: bump when the .pdmodel/.pdiparams/.pdconfig
# layout or contract changes shape.
FORMAT_VERSION = 1

# op name -> current version (unregistered ops are implicitly v1)
_versions: Dict[str, int] = {}
# (op, version) -> note describing the semantic change AT that bump
_notes: Dict[Tuple[str, int], str] = {}


class OpVersionError(UnimplementedError):
    """Artifact was produced by an incompatible (newer) runtime."""


def register_op_version(op: str, version: int, note: str = "") -> None:
    """Record that ``op``'s semantics changed at ``version`` (the
    reference REGISTER_OP_VERSION macro). Monotonic per op."""
    cur = _versions.get(op, 1)
    if version < cur:
        raise ValueError(f"op {op!r} version going backwards: "
                         f"{cur} -> {version}")
    _versions[op] = version
    if note:
        _notes[(op, version)] = note


def op_version(op: str) -> int:
    return _versions.get(op, 1)


def snapshot() -> dict:
    from .. import version as _v
    return {"format_version": FORMAT_VERSION,
            "framework_version": getattr(_v, "full_version", "0.0.0"),
            "op_versions": dict(_versions)}


def check_compat(saved: Optional[dict], source: str = "artifact") -> None:
    """Validate a loaded sidecar's compat block against this runtime.

    * missing block: pre-versioning artifact — warn, load anyway.
    * artifact format or any op version NEWER than the runtime: refuse
      (we cannot know the newer semantics).
    * op version OLDER than the runtime: warn with the notes of every
      bump in between (semantics changed since it was saved).
    """
    if not saved:
        warnings.warn(
            f"{source} carries no version metadata (saved by a "
            "pre-versioning build); loading as-is")
        return
    fmt = int(saved.get("format_version", 1))
    if fmt > FORMAT_VERSION:
        raise OpVersionError(
            f"{source} uses artifact format v{fmt}, this runtime "
            f"understands up to v{FORMAT_VERSION} — upgrade the "
            "framework to load it")
    changed: List[str] = []
    for op, v in (saved.get("op_versions") or {}).items():
        v = int(v)
        cur = op_version(op)
        if v > cur:
            raise OpVersionError(
                f"{source} was saved with {op} v{v}; this runtime has "
                f"v{cur} — upgrade the framework to load it")
        if v < cur:
            steps = [f"v{k}: {_notes[(op, k)]}"
                     for k in range(v + 1, cur + 1)
                     if (op, k) in _notes]
            changed.append(f"{op} v{v}->v{cur}"
                           + (f" ({'; '.join(steps)})" if steps else ""))
    if changed:
        warnings.warn(
            f"{source} was saved by an older runtime; op semantics "
            "changed since: " + "; ".join(changed))


# -- the project's own semantic-change history ------------------------------
# (reference analog: each REGISTER_OP_VERSION in the op's .cc file)

register_op_version(
    "flash_attention", 2,
    "r3: LSE layout fixed for real Mosaic lowering (lane-broadcast); "
    "outputs differ from v1 beyond fp tolerance on padded batches")
register_op_version(
    "nms", 2,
    "r2 advisor fix: category offsets use max-extent shifting, "
    "negative-coordinate boxes no longer collapse categories")
register_op_version(
    "box_coder", 2,
    "r2 advisor fix: axis=0/1 semantics corrected to reference "
    "(decode aligned the prior with the wrong dim before)")
register_op_version(
    "cross_entropy", 2,
    "r4: fluid soft_label branch computes the soft loss (was a shape "
    "error); clipped log for zero-probability classes")
