"""ParamAttr — parameter attribute bundle.

Analog of /root/reference/python/paddle/fluid/param_attr.py (ParamAttr,
WeightNormParamAttr): carries name, initializer, learning-rate scale,
regularizer, trainability and clip opt-in for a to-be-created parameter.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = True,
                 need_clip: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg) -> Optional["ParamAttr"]:
        """Normalize user input: None → default attr; False → no parameter
        (bias=False); str → named; initializer → wrapped (reference
        param_attr.py _to_attr semantics)."""
        if arg is None:
            return ParamAttr()
        if arg is False:
            return None
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        # duck-typed initializer
        if callable(arg):
            return ParamAttr(initializer=arg)
        raise TypeError(f"Cannot interpret {arg!r} as ParamAttr")
