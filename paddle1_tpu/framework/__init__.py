"""Framework-level utilities: save/load, ParamAttr, random seeding.

Analog of python/paddle/framework/ in the reference (io.py:494 save /
:688 load).
"""

from . import crypto
from .crypto import Cipher, CipherFactory, CipherUtils
from . import op_version
from .op_version import register_op_version
from .param_attr import ParamAttr
from .io import save, load
from ..core.generator import seed as _seed


class random:
    """paddle.framework.random compat namespace."""

    @staticmethod
    def get_rng_state():
        from ..core.generator import get_rng_state
        return get_rng_state()

    @staticmethod
    def set_rng_state(state):
        from ..core.generator import set_rng_state
        set_rng_state(state)
