"""Random number generation.

Analog of the reference's per-device Generator
(/root/reference/paddle/fluid/framework/generator.h:118-126) and the dygraph
tensor-parallel RNG tracker
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:23).

Design: TPU randomness is counter-based (threefry). A ``Generator`` owns a
root key + a monotone offset; every eager random op folds the offset in and
bumps it — so eager mode is reproducible under ``seed(n)`` just like the
reference's ``manual_seed``. Under ``jax.jit`` tracing, random ops must be
functional: the jit path threads an explicit key via ``rng_scope`` so that the
compiled program is deterministic in its key argument (no hidden state baked
into the trace).

``RNGStatesTracker`` reproduces the reference's model-parallel dropout
semantics: some random ops must agree across the tensor-parallel axis
(weight init), others must differ per rank (dropout on sharded activations);
tracked named states provide both.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax
import numpy as np

from .errors import AlreadyExistsError, NotFoundError

__all__ = [
    "Generator", "default_generator", "seed", "get_rng_state", "set_rng_state",
    "next_key", "rng_scope", "RNGStatesTracker", "get_rng_tracker",
]


class Generator:
    """Stateful key source for eager mode."""

    def __init__(self, seed_: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed_)

    def manual_seed(self, seed_: int) -> "Generator":
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed_) & 0xFFFFFFFFFFFFFFFF
            self._offset = 0
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state) -> None:
        self._seed = int(state["seed"])
        self._offset = int(state["offset"])

    def next_key(self) -> jax.Array:
        """Hand out a fresh key; bumps the offset (eager hot path)."""
        with self._lock:
            off = self._offset
            self._offset += 1
        # fold_in is cheap and gives an independent stream per offset.
        return jax.random.fold_in(jax.random.key(self._seed), off)

    def random(self) -> int:
        """A fresh python int (for seeding subprocess workers)."""
        k = self.next_key()
        return int(jax.random.bits(k, shape=(), dtype=np.uint32))


default_generator = Generator(0)


def seed(seed_: int) -> Generator:
    """Global manual seed (reference paddle.seed / manual_seed)."""
    get_rng_tracker().reset(seed_)
    return default_generator.manual_seed(seed_)


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state) -> None:
    default_generator.set_state(state)


# --- functional key threading for the jit path ------------------------------

_tls = threading.local()


@contextlib.contextmanager
def rng_scope(key: jax.Array):
    """Inside this scope, ``next_key()`` splits from ``key`` functionally
    instead of consuming global state — required under jit tracing."""
    prev = getattr(_tls, "scope", None)
    _tls.scope = [key, 0]
    try:
        yield
    finally:
        _tls.scope = prev


def next_key() -> jax.Array:
    """The one entry point random ops use to obtain a key."""
    scope = getattr(_tls, "scope", None)
    if scope is not None:
        key, n = scope
        scope[1] = n + 1
        return jax.random.fold_in(key, n)
    return default_generator.next_key()


def in_rng_scope() -> bool:
    return getattr(_tls, "scope", None) is not None


# --- tensor-parallel RNG state tracker --------------------------------------

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    """Named generator states for tensor parallelism.

    ``add(name, seed)`` registers a stream; ``rng_state(name)`` temporarily
    swaps the default generator to it (reference random.py:23 semantics:
    dropout inside ColumnParallelLinear uses a per-rank stream; everything
    else uses the replicated global stream)."""

    def __init__(self):
        self._states: Dict[str, Generator] = {}
        self._seeds: set = set()

    def reset(self, base_seed: Optional[int] = None) -> None:
        self._states.clear()
        self._seeds.clear()

    def add(self, name: str, seed_: int) -> None:
        if name in self._states:
            raise AlreadyExistsError(f"RNG state {name!r} already exists")
        if seed_ in self._seeds:
            # reference random.py:40 — two streams sharing a seed would
            # silently draw identical masks, the exact bug this guards
            raise AlreadyExistsError(f"RNG seed {seed_} already used by "
                                     "another tracked state")
        self._seeds.add(seed_)
        self._states[name] = Generator(seed_)

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        if name not in self._states:
            raise NotFoundError(
                f"RNG state {name!r} not registered; call add() first")
        gen = self._states[name]
        if in_rng_scope():
            # jit path: stay functional — derive a per-name subkey from
            # the scope key so the trace is deterministic in its key
            # argument and distinct per tracked stream. The OUTER
            # counter advances too, so repeated rng_state regions in
            # one trace (the per-layer dropout pattern) draw distinct
            # subkeys instead of restarting the same stream.
            scope = getattr(_tls, "scope", None)
            n = scope[1]
            scope[1] = n + 1
            sub = jax.random.fold_in(
                jax.random.fold_in(scope[0], n),
                gen.initial_seed & 0x7FFFFFFF)
            with rng_scope(sub):
                yield
            return
        global default_generator
        prev = default_generator
        default_generator = gen
        try:
            yield
        finally:
            default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _tracker
