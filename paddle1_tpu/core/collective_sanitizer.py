"""Runtime collective-schedule sanitizer (ISSUE 14).

The static passes (``tools/lint/rank_divergence.py``,
``commit_protocol.py``) see lexical shapes; they cannot see a schedule
composed across helpers at runtime — a retry loop that re-enters a
barrier on one rank only, a data-dependent branch that skips an
all-reduce. A real divergence on hardware HANGS: rank 0 waits at a
rendezvous its peers never reach, and the job wedges until a hang
timeout fires with no pointer at the cause. This module makes that
divergence a deterministic, typed, CPU-testable failure, the
``core/locks.py`` way: one flag (``debug_collective_sanitizer``),
structurally zero cost off, loud on.

* **Per-rank schedule journal** — every collective wrapper
  (``distributed/collective.py``) and the checkpoint commit barrier
  call :func:`note_collective`, which records
  ``(seq, site, op, tree-shape digest)`` — and appends it as one JSONL
  line to ``collective-<rank>.jsonl`` under the journal dir. The
  journal is the rank's claimed SPMD schedule, written even where the
  collective is an eager no-op (single process, CPU) — which is
  exactly what makes the multi-rank deadlock testable on a laptop:
  the schedules diverge even though nothing blocks.

* **Cross-rank verifier** — :func:`verify_dir` /
  :class:`JournalWatcher` compare every rank's journal against rank
  0's (well, the lowest recorded rank's) and raise the typed
  :class:`CollectiveDivergenceError` naming the FIRST diverging step,
  both ranks' entries at it, and each side's surrounding schedule.
  The Supervisor polls a watcher each sweep when the flag is on
  (incremental — per-file offsets, no re-reads), and
  ``python -m tools.collective_verify <dir>`` runs the full check
  (including completion: a rank whose journal simply STOPS while
  peers continue is the would-be deadlock) from the command line.

* **Journal-dir plumbing** — the Supervisor stamps
  ``FLAGS_debug_collective_sanitizer`` and the ``PADDLE_COLLECTIVE_
  JOURNAL`` dir env into worker envs; the worker's sanitizer consumes
  (pops) the dir env when it arms, so grandchildren (loader worker
  processes) can never journal onto the rank's file — the PR 3
  heartbeat-env lesson. A grandchild that inherits only the flag
  records in memory and writes nothing.

Off (the default) is structurally free: :func:`note_collective` is one
module-bool test, no journal file is ever created, and the collective
wrappers are plain pass-throughs (the zero-cost test pins all three).
The armed latch derives from the flag at import (workers: the
Supervisor's ``FLAGS_`` env) and at :func:`reset` (in-process tests:
``flags_guard`` + ``reset()``), mirroring ``core/jit_sanitizer``.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .errors import EnforceNotMet

__all__ = ["CollectiveDivergenceError", "JOURNAL_ENV", "sanitizing",
           "note_collective", "schedule", "reset", "journal_path",
           "read_journal", "verify_dir", "verify_schedules",
           "JournalWatcher", "journal_file_name", "journal_rank_count"]


class CollectiveDivergenceError(EnforceNotMet):
    """Two ranks claim different collective schedules — the SPMD
    deadlock class, made loud before anything blocks."""


# the Supervisor stamps this into worker envs; the worker's sanitizer
# POPS it at arm time so grandchild processes cannot inherit it and
# journal onto the rank's file
JOURNAL_ENV = "PADDLE_COLLECTIVE_JOURNAL"

_lock = threading.Lock()
_armed = False
_seq = 0
_records: List[Dict[str, Any]] = []
_journal_dir: str = ""
_rank: int = 0
# the worker's restart incarnation (PR 3 env protocol): journals are
# PER-INCARNATION files, because a resized/restarted world replays its
# schedule from the resume point — appending the replay onto the old
# life's journal would read as a false divergence against peers whose
# old lives ended elsewhere. Each epoch verifies within itself.
_incarnation: int = 0
_fh = None


def sanitizing() -> bool:
    """Whether the ``debug_collective_sanitizer`` flag is on (read at
    arm time — the hot path tests the module bool, not the flag)."""
    from . import flags as core_flags
    return bool(core_flags.flag("debug_collective_sanitizer"))


def _env_rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        return 0


def _env_incarnation() -> int:
    """The worker's restart incarnation: health's adopted value when
    its channel already installed, else a PEEK at the env (never a
    pop — the heartbeat channel owns consuming it)."""
    from . import health
    try:
        if getattr(health, "_installed", False):
            return int(health.incarnation())
        return int(os.environ.get(health.INCARNATION_ENV, "0") or 0)
    except (ValueError, AttributeError):  # pragma: no cover
        return 0


def reset() -> None:
    """Drop the recorded schedule, close the journal, and re-derive the
    armed latch from the CURRENT flag (test isolation — and the
    in-process way to arm after ``set_flags``: the latch otherwise
    derives once at import, where workers get it from the
    Supervisor-stamped env). Re-reads ``PADDLE_COLLECTIVE_JOURNAL``
    (consuming it) / the ``collective_journal_dir`` flag and the
    rank env."""
    global _armed, _seq, _journal_dir, _rank, _incarnation, _fh
    with _lock:
        _records.clear()
        _seq = 0
        if _fh is not None:
            try:
                _fh.close()
            except OSError:  # pragma: no cover
                pass
            _fh = None
        _armed = sanitizing()
        _journal_dir = ""
        if _armed:
            # consume the dir env: grandchildren must NOT inherit it
            # (they'd interleave their schedule into the rank's file)
            env_dir = os.environ.pop(JOURNAL_ENV, "")
            if not env_dir:
                from . import flags as core_flags
                env_dir = core_flags.flag("collective_journal_dir")
            _journal_dir = env_dir or ""
            _rank = _env_rank()
            _incarnation = _env_incarnation()


def journal_file_name(rank: int, incarnation: int = 0) -> str:
    """Per-rank, per-incarnation journal name: a restarted/resized
    life writes a FRESH file (``.r<n>`` suffix) — its replayed
    schedule is a new epoch, not an append onto the old life's."""
    if incarnation:
        return f"collective-{rank}.r{incarnation}.jsonl"
    return f"collective-{rank}.jsonl"


def journal_path() -> Optional[str]:
    """This process's journal file (None when unarmed or in-memory)."""
    if not _armed or not _journal_dir:
        return None
    return os.path.join(_journal_dir,
                        journal_file_name(_rank, _incarnation))


def _shape_spec(args: Iterable[Any]) -> str:
    """Compact tree-shape text of the collective's tensor arguments:
    ``f32[4,8];i32[2]``. Only shape/dtype ride the digest — values
    legitimately differ per rank, shapes must not."""
    parts: List[str] = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None and isinstance(a, (list, tuple)):
            parts.append(f"seq{len(a)}")
            continue
        if shape is None:
            parts.append(type(a).__name__)
            continue
        d = str(dtype) if dtype is not None else "?"
        parts.append(f"{d}[{','.join(str(int(s)) for s in shape)}]")
    return ";".join(parts)


def _caller_site(depth: int) -> str:
    """``file.py:line`` of the frame ``depth`` levels above
    note_collective — computed only when armed."""
    try:
        fr = sys._getframe(depth + 1)  # +1 for this helper
        return (f"{os.path.basename(fr.f_code.co_filename)}:"
                f"{fr.f_lineno}")
    except ValueError:  # pragma: no cover - shallow stack
        return "?"


def note_collective(op: str, args: Iterable[Any] = (),
                    site: Optional[str] = None, depth: int = 1) -> None:
    """Record one collective op this process claims to perform. Free
    when unarmed (one module-bool test). ``site`` defaults to the
    frame ``depth`` levels above this function (1 = the direct
    caller); the collective wrappers route through a shared helper and
    pass 3 — ``note_collective ← helper ← wrapper ← USER`` — so the
    journal names the user's call line, not the wrapper's."""
    global _seq, _fh
    if not _armed:
        return
    spec = _shape_spec(args)
    digest = hashlib.sha1(spec.encode()).hexdigest()[:10]
    if site is None:
        site = _caller_site(depth)
    with _lock:
        _seq += 1
        rec = {"seq": _seq, "site": site, "op": op, "shape": spec,
               "digest": digest}
        _records.append(rec)
        if _journal_dir:
            if _fh is None:
                os.makedirs(_journal_dir, exist_ok=True)
                _fh = open(os.path.join(
                    _journal_dir,
                    journal_file_name(_rank, _incarnation)), "a")
            _fh.write(json.dumps(rec) + "\n")
            _fh.flush()


def schedule() -> List[Dict[str, Any]]:
    """Copy of this process's recorded schedule (test hook)."""
    with _lock:
        return [dict(r) for r in _records]


# -- cross-rank verification --------------------------------------------------


def _entry_key(rec: Dict[str, Any]) -> Tuple[str, str, str]:
    return (str(rec.get("site", "?")), str(rec.get("op", "?")),
            str(rec.get("digest", "?")))


def _entry_text(rec: Optional[Dict[str, Any]]) -> str:
    if rec is None:
        return "<no entry — schedule ends>"
    shape = rec.get("shape")
    if not shape:  # barrier-style ops carry no tensor args
        shape = "no args"
    return f"{rec.get('op')} @ {rec.get('site')} [{shape}]"


def _window(records: List[Dict[str, Any]], idx: int,
            span: int = 2) -> str:
    lo = max(0, idx - span)
    out = []
    for i in range(lo, min(len(records), idx + span + 1)):
        mark = ">>" if i == idx else "  "
        out.append(f"    {mark} #{i + 1} {_entry_text(records[i])}")
    if idx >= len(records):
        out.append(f"    >> #{idx + 1} {_entry_text(None)}")
    return "\n".join(out)


def verify_schedules(by_rank: Dict[int, List[Dict[str, Any]]],
                     complete: bool = False, start: int = 0) -> int:
    """Compare every rank's claimed schedule against the lowest rank's.
    Returns the number of verified steps (the common prefix length).
    Raises :class:`CollectiveDivergenceError` naming the first
    diverging step when two ranks disagree — and, with
    ``complete=True`` (the job-end/CLI mode), when one rank's schedule
    simply STOPS while another continues (the would-be deadlock: the
    longer rank waits at a rendezvous the shorter one never reaches).
    ``start`` skips an already-verified prefix (the watcher's
    incremental mode) — entries before it are trusted, not re-read.
    """
    if len(by_rank) < 2:
        return len(next(iter(by_rank.values()))) if by_rank else 0
    ranks = sorted(by_rank)
    ref_rank = ranks[0]
    ref = by_rank[ref_rank]
    verified = len(ref)
    for r in ranks[1:]:
        recs = by_rank[r]
        n = min(len(ref), len(recs))
        for i in range(start, n):
            if _entry_key(ref[i]) != _entry_key(recs[i]):
                raise CollectiveDivergenceError(
                    f"collective schedules diverge at step {i + 1}: "
                    f"rank {ref_rank} performed "
                    f"{_entry_text(ref[i])} while rank {r} performed "
                    f"{_entry_text(recs[i])} — on hardware the ranks "
                    "would deadlock at this rendezvous. Schedules "
                    "around the divergence:\n"
                    f"  rank {ref_rank}:\n{_window(ref, i)}\n"
                    f"  rank {r}:\n{_window(recs, i)}")
        if complete and len(ref) != len(recs):
            longer_rank, longer = ((ref_rank, ref) if len(ref) > n
                                   else (r, recs))
            shorter_rank = r if longer_rank == ref_rank else ref_rank
            raise CollectiveDivergenceError(
                f"collective schedules diverge at step {n + 1}: rank "
                f"{shorter_rank}'s schedule ends after {n} "
                f"collective(s) while rank {longer_rank} continues "
                f"with {_entry_text(longer[n])} — rank {longer_rank} "
                "would block at that rendezvous forever. Schedules "
                "around the divergence:\n"
                f"  rank {longer_rank}:\n{_window(longer, n)}\n"
                f"  rank {shorter_rank}:\n"
                f"{_window(by_rank[shorter_rank], n)}")
        verified = min(verified, n)
    return verified


def read_journal(path: str) -> List[Dict[str, Any]]:
    """Parse one rank's journal; a torn final line (the writer was
    killed mid-record) is skipped, never crashed on."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue  # torn write
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _journal_files(directory: str) -> Dict[int, Dict[int, str]]:
    """``{incarnation_epoch: {rank: path}}`` for every per-rank
    journal under ``directory``. Each restart/resize epoch verifies
    within itself: a resized world replays its schedule from the
    resume point, which is a NEW epoch, not a continuation."""
    out: Dict[int, Dict[int, str]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("collective-")
                and name.endswith(".jsonl")):
            continue
        mid = name[len("collective-"):-len(".jsonl")]
        rank_s, _, inc_s = mid.partition(".r")
        try:
            rank = int(rank_s)
            inc = int(inc_s) if inc_s else 0
        except ValueError:
            continue
        out.setdefault(inc, {})[rank] = os.path.join(directory, name)
    return out


def journal_rank_count(directory: str) -> int:
    """Ranks journaled in the busiest epoch (the CLI's ≥2 gate)."""
    files = _journal_files(directory)
    return max((len(v) for v in files.values()), default=0)


def verify_dir(directory: str, complete: bool = False) -> int:
    """Verify every per-rank journal under ``directory``, each
    incarnation epoch within itself (see :func:`verify_schedules`).
    Returns total verified steps across epochs; 0 when no epoch holds
    two ranks to compare."""
    total = 0
    for inc, by_rank in sorted(_journal_files(directory).items()):
        if len(by_rank) < 2:
            continue
        total += verify_schedules(
            {r: read_journal(p) for r, p in by_rank.items()},
            complete=complete)
    return total


class JournalWatcher:
    """Incremental cross-rank verifier for a live journal dir — what
    the Supervisor polls each sweep. Keeps per-file byte offsets so a
    poll reads only NEW records, and a per-epoch verified-prefix
    cursor so already-agreed steps are never re-compared (a long run
    stays O(records), not O(records x sweeps)). Ranks mid-run are
    legitimately at different positions, so :meth:`poll` compares
    only the common prefix (divergence in it is already fatal);
    :meth:`final` adds the completion check for a cleanly finished
    job — a schedule that simply STOPS short of its peers' is the
    would-be deadlock."""

    def __init__(self, directory: str):
        self.directory = directory
        self._offsets: Dict[Tuple[int, int], int] = {}
        # epoch -> rank -> records
        self._epochs: Dict[int, Dict[int, List[Dict[str, Any]]]] = {}
        # epoch -> (verified steps, rank count at verification time —
        # a rank joining late must re-verify from 0 against everyone)
        self._verified: Dict[int, Tuple[int, int]] = {}

    def _ingest(self) -> None:
        for inc, by_rank in _journal_files(self.directory).items():
            for rank, path in by_rank.items():
                off = self._offsets.get((inc, rank), 0)
                try:
                    with open(path, "rb") as f:  # byte offsets: exact
                        f.seek(off)
                        chunk = f.read()
                except OSError:
                    continue
                recs = self._epochs.setdefault(inc, {}).setdefault(
                    rank, [])
                consumed = 0
                for raw in chunk.splitlines(keepends=True):
                    if not raw.endswith(b"\n"):
                        break  # torn tail: re-read next poll
                    consumed += len(raw)
                    ln = raw.decode("utf-8", errors="replace").strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        recs.append(rec)
                self._offsets[(inc, rank)] = off + consumed

    def _verify(self, complete: bool) -> int:
        total = 0
        for inc, by_rank in sorted(self._epochs.items()):
            if len(by_rank) < 2:
                continue
            done, nranks = self._verified.get(inc, (0, 0))
            if nranks != len(by_rank):
                done = 0  # a new rank appeared: its prefix is unseen
            n = verify_schedules(by_rank, complete=complete,
                                 start=done)
            self._verified[inc] = (n, len(by_rank))
            total += n
        return total

    def poll(self) -> int:
        """Ingest new records and verify the (new part of the) common
        prefix. Raises :class:`CollectiveDivergenceError` on
        divergence."""
        self._ingest()
        return self._verify(complete=False)

    def final(self) -> int:
        """Job-end verification including the completion check."""
        self._ingest()
        return self._verify(complete=True)


# arm at import: workers reach here with the Supervisor-stamped
# FLAGS_/journal env already in place (in-process enabling goes through
# flags_guard/set_flags + reset(), the jit_sanitizer idiom)
reset()
