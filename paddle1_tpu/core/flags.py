"""Global flag registry.

TPU-native analog of the reference's gflags-based registry
(/root/reference/paddle/fluid/platform/flags.cc:33-461 and the Python bridge
python/paddle/fluid/framework.py:6083 set_flags / :6106 get_flags).

Design: a single process-wide registry of typed flags. Flags can be set
programmatically (``set_flags``) or via environment variables named
``FLAGS_<name>`` (checked at definition time, mirroring gflags env binding).
Unlike the reference there is no C++/Python split: the registry is the single
source of truth and is consulted by the runtime (allocator hints, determinism,
nan/inf checking, collective timeouts, ...).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .errors import InvalidArgumentError

__all__ = ["define_flag", "get_flags", "set_flags", "flag", "flags_guard",
           "maybe_enable_compilation_cache"]


@dataclass
class _FlagDef:
    name: str
    default: Any
    help: str
    type: type
    value: Any
    validator: Optional[Callable[[Any], bool]] = None


_registry: Dict[str, _FlagDef] = {}
_lock = threading.RLock()


def _coerce(raw: Any, ty: type) -> Any:
    if ty is bool:
        if isinstance(raw, bool):
            return raw
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return ty(raw)


def define_flag(name: str, default: Any, help: str = "",
                validator: Optional[Callable[[Any], bool]] = None) -> None:
    """Register a flag. Environment variable ``FLAGS_<name>`` overrides the
    default at definition time (gflags-compatible behavior)."""
    with _lock:
        ty = type(default)
        value = default
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            value = _coerce(env, ty)
        if validator is not None and not validator(value):
            raise InvalidArgumentError(
                f"Invalid value {value!r} for flag {name}")
        _registry[name] = _FlagDef(name, default, help, ty, value, validator)


def flag(name: str) -> Any:
    """Fast single-flag read used by runtime internals."""
    try:
        return _registry[name].value
    except KeyError:
        raise InvalidArgumentError(
            f"Flag '{name}' has not been defined. Known flags: "
            f"{sorted(_registry)[:20]} ...") from None


def get_flags(flags) -> Dict[str, Any]:
    """Query flag values. ``flags`` may be a name or list of names."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        out[name] = flag(name)
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flag values from a dict, with type coercion and validation."""
    if not isinstance(flags, dict):
        raise InvalidArgumentError("set_flags expects a dict of {name: value}")
    with _lock:
        for name, value in flags.items():
            if name not in _registry:
                raise InvalidArgumentError(f"Flag '{name}' is not defined")
            d = _registry[name]
            value = _coerce(value, d.type)
            if d.validator is not None and not d.validator(value):
                raise InvalidArgumentError(
                    f"Invalid value {value!r} for flag {name}")
            d.value = value


def flag_active(name: str) -> bool:
    """Resolve an auto/always/never flag against the backend: True when
    ``always``, or when ``auto`` and the default backend is TPU. The
    shared idiom behind the Pallas-kernel gates and the channels-last
    region."""
    v = flag(name)
    if v == "always":
        return True
    if v == "auto":
        import jax
        return jax.default_backend() == "tpu"
    return False


def conv_nhwc_active() -> bool:
    """Whether NCHW-API image ops should execute channels-last
    internally (the conv_nhwc flag resolved against the backend)."""
    return flag_active("conv_nhwc")


_compilation_cache_wired = False


def maybe_enable_compilation_cache() -> bool:
    """Wire the jax persistent compilation cache from the ``jit_cache_dir``
    flag (idempotent; returns True when the cache was enabled by THIS
    call). Called from ParallelEngine.__init__ so every compiled trainer
    picks it up without user code; safe no-op when the flag is empty or
    the jax build lacks the config knobs."""
    global _compilation_cache_wired
    with _lock:
        if _compilation_cache_wired:
            return False
        cache_dir = flag("jit_cache_dir")
        if not cache_dir:
            # don't latch: the flag may be set later (set_flags between
            # engine constructions must still wire the cache)
            return False
        _compilation_cache_wired = True
    import warnings

    import jax
    try:
        cache_dir = os.path.expanduser(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(flag("jit_cache_min_compile_time_s")))
        except AttributeError:
            pass  # older jax: only the dir knob exists
        try:
            # also cache CPU executables (tests / the virtual mesh); TPU
            # and GPU are cached by default once the dir is set
            jax.config.update("jax_persistent_cache_enable_xla_caches",
                              "all")
        except AttributeError:
            pass
        return True
    except Exception as e:  # never let cache plumbing break training
        warnings.warn(f"persistent compilation cache disabled: {e}")
        return False


class flags_guard:
    """Context manager that temporarily overrides flags (test helper)."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None, **kw):
        self._overrides = {**(overrides or {}), **kw}
        self._saved: Dict[str, Any] = {}

    def __enter__(self):
        self._saved = get_flags(list(self._overrides))
        set_flags(self._overrides)
        return self

    def __exit__(self, *exc):
        set_flags(self._saved)
        return False


def _define_builtin_flags() -> None:
    # Numerics / debugging (reference: platform/flags.cc check_nan_inf).
    # NOTE (ISSUE 11 dead-flag audit): the reference-compat no-ops
    # `deterministic`, `allocator_strategy` and
    # `fraction_of_gpu_memory_to_use` were DELETED — they validated and
    # did nothing (the VERDICT dead-flag class); XLA:TPU lowering is
    # deterministic by construction and memory is XLA/PJRT-managed
    # (XLA_PYTHON_CLIENT_MEM_FRACTION). See MIGRATING.md.
    define_flag("check_nan_inf", False,
                "Sweep op outputs for NaN/Inf after every eager op.")
    define_flag("debug_lock_sanitizer", False,
                "Runtime lock-order sanitizer (core/locks.py): hot-"
                "class locks built through core.locks.make_lock become "
                "order-recording wrappers — acquiring two locks in "
                "opposite orders anywhere in the process raises typed "
                "LockOrderError at the second site, and a marked "
                "blocking call (wire recv, future wait) while holding "
                "one raises BlockingUnderLockError. Off (the default) "
                "is structurally free: make_lock returns a plain "
                "threading.Lock. Enabled for the CI concurrency "
                "lanes.")
    define_flag("debug_collective_sanitizer", False,
                "Runtime collective-schedule sanitizer (core/"
                "collective_sanitizer.py): every collective wrapper "
                "(distributed/collective.py) and the checkpoint "
                "commit barrier journal (seq, site, op, tree-shape "
                "digest) per rank; the cross-rank verifier — polled "
                "by the Supervisor each sweep and runnable via "
                "python -m tools.collective_verify — raises typed "
                "CollectiveDivergenceError naming the first step "
                "where two ranks' schedules disagree, so the "
                "rank-divergent collective that HANGS on hardware "
                "becomes a deterministic CPU-testable failure. Off "
                "(the default) is structurally free: note_collective "
                "is one module-bool test and no journal file is ever "
                "created. Enabled for the CI debug-sanitizers lane. "
                "The Supervisor forwards FLAGS_debug_collective_"
                "sanitizer plus the journal-dir env to workers; the "
                "worker consumes the dir env at arm time so "
                "grandchildren never journal onto the rank's file.")
    define_flag("collective_journal_dir", "",
                "Where the collective-schedule sanitizer writes its "
                "per-rank collective-<rank>.jsonl journals. Empty "
                "(the default): a supervised worker uses the dir the "
                "Supervisor stamped into PADDLE_COLLECTIVE_JOURNAL "
                "(derived from its log/heartbeat dir), and an "
                "unsupervised armed process records in memory only "
                "(schedule() still works; no files).")
    define_flag("debug_jit_sanitizer", False,
                "Runtime JIT-discipline sanitizer (core/jit_sanitizer"
                ".py): engine/serving/generate jit entry points raise "
                "typed RetraceStormError when one site compiles more "
                "distinct signatures than its limit (the "
                "jit_retrace_warn warn-once upgraded to enforceable), "
                "donated buffers are poisoned (deleted) after every "
                "donating dispatch so use-after-donate fails "
                "deterministically with typed UseAfterDonateError "
                "naming the donation site — on CPU donation silently "
                "no-ops, which is how the PR 1 aliasing bug passed "
                "tests — and host-sync events (loss readbacks, decode "
                "token fetches) are counted per hot section. Off (the "
                "default) is structurally free: site() returns None "
                "and wrap_donating() returns the function unchanged. "
                "Enabled for the CI debug-sanitizers lane.")
    # Eager engine
    define_flag("eager_max_tape_len", 1_000_000,
                "Safety valve on the autograd graph: an eager "
                "process holding more than this many LIVE grad nodes "
                "(ops recorded, backward never run) fails loudly in "
                "autograd.engine instead of growing host memory "
                "unboundedly.",
                validator=lambda v: v >= 1)
    define_flag("retain_grad_for_all", False,
                "Retain .grad for non-leaf tensors (debugging).")
    # Collectives
    define_flag("collective_timeout_s", 1800.0,
                "Distributed rendezvous bound: passed to "
                "jax.distributed.initialize as initialization_timeout "
                "by init_parallel_env (a worker that cannot reach the "
                "coordinator fails after this many seconds instead of "
                "blocking the pod forever).",
                validator=lambda v: v > 0)
    define_flag("hierarchical_allreduce", False,
                "Default for DistributedStrategy."
                "use_hierarchical_allreduce: prefer ICI-then-DCN "
                "hierarchical collectives (collective."
                "hierarchical_all_reduce) on multislice topologies.")
    # Profiler
    define_flag("profiler_trace_dir", "",
                "Default log_dir for profiler.start_profiler: when set "
                "and start_profiler is called without an explicit "
                "log_dir, the device (XLA) trace is written here. "
                "Empty (the default) keeps start_profiler host-only "
                "unless a log_dir is passed. The cross-process span "
                "sink is obs_trace_dir; this flag only routes the "
                "jax.profiler device trace.")
    # JIT
    define_flag("jit_donate_params", True,
                "Donate parameter buffers in compiled training steps.")
    define_flag("jit_cache_dir", "",
                "Persistent XLA compilation-cache directory (wired into "
                "jax.config by maybe_enable_compilation_cache, called "
                "from ParallelEngine init). Empty disables. Amortizes "
                "the multi-minute BERT-scale compiles across processes "
                "— the dispatch-side half of the multi-step training "
                "story (the per-step half is engine.step_many).")
    define_flag("jit_cache_min_compile_time_s", 1.0,
                "Only persist executables whose compile took at least "
                "this many seconds (tiny kernels are cheaper to rebuild "
                "than to deserialize).",
                validator=lambda v: v >= 0)
    define_flag("jit_retrace_warn", True,
                "Warn (once per engine) when ParallelEngine.step/"
                "step_many retraces because a batch arrived with a new "
                "shape signature — each retrace is a full XLA recompile "
                "that silently re-serializes the host loop.")
    define_flag("dy2static", True,
                "Rewrite tensor-dependent Python control flow (if/while/"
                "for-range, and/or/not) into lax.cond/while_loop under "
                "jit.to_static (reference ProgramTranslator.enable analog)."
                " Read at DECORATION time: set it before @to_static runs "
                "(module import), not per call.")
    # Fused kernels (reference operators/fused/ role)
    define_flag("flash_attention", "auto",
                "Pallas flash attention: auto (TPU only, AND only when "
                "the dense score tensor would exceed "
                "flash_auto_score_mb — the r5 on-chip crossover sweep "
                "showed XLA's fused dense attention is faster at every "
                "compute-bound length, 1.25x at seq 128 up to 2.1x at "
                "seq 4096, so flash earns its place purely as the "
                "long-sequence memory escape; "
                "chip_results/flash_crossover.txt), always "
                "(interpret-mode on CPU, for tests), never.",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("flash_auto_score_mb", 65536.0,
                "Estimated transient attention memory (MiB) above which "
                "flash_attention=auto switches from XLA dense attention "
                "to the Pallas flash kernels: batch*heads*seq_q*seq_k *"
                " (2*compute-dtype itemsize + 8) bytes. The r5 on-chip "
                "sweeps found XLA's internally-fused dense attention "
                "FASTER at every measured shape — seq 128 through "
                "16384 causal fwd+bwd, including estimates (18-36 GiB) "
                "far past physical HBM, because XLA streams the "
                "softmax without materializing the scores. The 64 GiB "
                "default therefore routes everything measured to "
                "dense; flash remains the escape for regimes beyond "
                "measurement (and 'always' forces it).",
                validator=lambda v: v > 0)
    define_flag("pallas_paged_attention", "auto",
                "Pallas paged-attention gather kernel for the paged "
                "decode path (serve_gen_paged): auto (TPU only — the "
                "scalar-prefetch page gather skips the dense "
                "[slots, pages*page_size] materialization XLA's take-"
                "based composition pays), always (interpret-mode on "
                "CPU, for tests), never (XLA gather composition).",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("fused_layer_norm", "auto",
                "Pallas fused LayerNorm: auto (TPU only), always, never.",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("fused_bn", "auto",
                "Pallas fused batch norm (one kernel for stats + "
                "normalize + activation + residual-add, the reference "
                "fused_bn_activation_op/fused_bn_add_activation_op "
                "role): auto (TPU only, AND only when the channels-"
                "last activation is at least fused_bn_auto_mb — small "
                "BNs are latency-bound and XLA's fusion handles them; "
                "the crossover lives where the multi-pass stat chain "
                "becomes HBM-bound, ~46% of the ResNet-50 step in "
                "chip_results/resnet_trace_b32.txt), always "
                "(interpret-mode on CPU, for tests and the "
                "bench.py --conv-block gate), never (the XLA lowering "
                "— the ablation arm for the next chip window). "
                "Requires a channels-last layout (NHWC data_format or "
                "the conv_nhwc region) and affine weight+bias.",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("fused_bn_auto_mb", 4.0,
                "Crossover threshold (MiB of the BN input activation) "
                "below which fused_bn=auto keeps the XLA lowering: "
                "under it the stat passes fit the compiler's fusion "
                "budget and kernel launch overhead dominates; above "
                "it each extra pass is a full HBM round-trip. "
                "PROVISIONAL until the next chip window's sweep "
                "(chip_results/NOTES.md) — 'always'/'never' bypass it "
                "for A/B runs.",
                validator=lambda v: v > 0)
    define_flag("fused_bn_bwd", "auto",
                "Pallas fused batch-norm BACKWARD (one-pass "
                "dx/dgamma/dbeta): auto (TPU only), always (interpret "
                "on CPU), never (XLA composition backward — the "
                "ablation arm; forward fusion still applies). Only "
                "consulted when the forward ran the fused kernel.",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("fused_adam", "never",
                "Pallas fused Adam/AdamW update: auto (TPU only), "
                "always, never. Default never since the r5 on-chip "
                "ablation: XLA's plain update chain beat the Pallas "
                "kernel by ~7 MFU points on BERT-base (1528 vs 1373 "
                "samples/s) — the compiler fuses the elementwise "
                "moment/param updates better than the hand-tiled slab "
                "kernel on this backend (BASELINE.md r5).",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("fused_softmax", "auto",
                "Pallas fused softmax: auto (TPU only), always, never.",
                validator=lambda v: v in ("auto", "always", "never"))
    define_flag("flash_backward", "auto",
                "Pallas flash-attention BACKWARD kernels: auto (TPU "
                "only), always (interpret on CPU), never (XLA recompute "
                "backward). Default 'auto' since the Mosaic lowering "
                "passed the on-chip smoke (tools/tpu_kernel_smoke.py, "
                "r5: all dq/dk/dv variants max_err=0 vs the XLA "
                "recompute backward on TPU v5 lite).",
                validator=lambda v: v in ("auto", "always", "never"))
    # Fault tolerance (reference incubate/auto_checkpoint +
    # update_loss_scaling roles; consumed by distributed.resilience and
    # core.chaos)
    define_flag("ft_bad_step_policy", "raise",
                "What ResilientTrainer does when the device-side "
                "isfinite flag (or the divergence watchdog) marks a "
                "step bad: raise (fail loudly; params keep their last "
                "good values because the compiled step skips non-finite "
                "updates on device), skip (count it and move on), "
                "restore_last_good (roll back to the last checkpoint "
                "and replay the data stream from there).",
                validator=lambda v: v in ("raise", "skip",
                                          "restore_last_good"))
    define_flag("ft_max_retries", 3,
                "Transient-failure retries around a train step or "
                "checkpoint write before the error propagates.",
                validator=lambda v: v >= 0)
    define_flag("ft_backoff_base_s", 0.5,
                "First retry backoff; doubles per retry (capped by "
                "ft_backoff_max_s).",
                validator=lambda v: v >= 0)
    define_flag("ft_backoff_max_s", 10.0,
                "Backoff ceiling for the exponential retry schedule.",
                validator=lambda v: v >= 0)
    define_flag("ft_save_freq", 100,
                "ResilientTrainer default checkpoint period in steps.",
                validator=lambda v: v >= 1)
    define_flag("ft_ps_max_retries", 5,
                "RemoteTable transport retries (reconnect + replay "
                "through the push-epoch fence) before a table-server "
                "call raises typed PsUnavailableError. Sized to cover "
                "a Supervisor restart-from-checkpoint of the PS "
                "worker: a server death mid-pull/push is a stall, not "
                "a trainer crash (reference: PSERVER relaunch + "
                "worker reconnect).",
                validator=lambda v: v >= 0)
    define_flag("ft_ps_backoff_base_s", 0.05,
                "First RemoteTable retry backoff; doubles per attempt "
                "(capped by ft_ps_backoff_max_s).",
                validator=lambda v: v >= 0)
    define_flag("ft_ps_backoff_max_s", 2.0,
                "Backoff ceiling for the RemoteTable retry schedule.",
                validator=lambda v: v >= 0)
    define_flag("ft_divergence_factor", 0.0,
                "Loss-explosion watchdog: a finite loss greater than "
                "factor * running-mean counts as a bad step (0 "
                "disables). Costs nothing extra: the loss rides the "
                "same packed readback as the isfinite flag.",
                validator=lambda v: v >= 0)
    define_flag("ft_supervise", "",
                "Elastic launcher supervision policy (empty/off disables "
                "and keeps the plain fail-fast watch loop without "
                "heartbeats). fail_fast: any worker death/hang/unhealthy "
                "report kills the pod (today's semantics plus hang "
                "DETECTION). restart: SIGKILL the failed/hung rank and "
                "relaunch it with the same env up to "
                "ft_max_worker_restarts times; the relaunched worker "
                "resumes from the last committed checkpoint "
                "(ResilientTrainer.restore_latest), which the elastic "
                "parity gate holds to 1e-6 — in a multi-worker world a "
                "failed rank instead routes into the RESIZE path "
                "(shrink-and-continue; see 'resize'). drain: request "
                "graceful preemption (SIGTERM -> "
                "chaos.request_preemption), let every worker "
                "checkpoint, then stop. resize: membership change is a "
                "recoverable event — on worker loss (or an explicit "
                "Supervisor.request_resize) the surviving ranks are "
                "drained so each commits a final checkpoint, the "
                "dp/sharding mesh is recomputed for the new world size, "
                "param/optimizer state reshards via the manifest-driven "
                "remap, and the fleet relaunches at the new size with "
                "resume-from-latest.",
                validator=lambda v: v in ("", "off", "fail_fast",
                                          "restart", "drain", "resize"))
    define_flag("ft_hang_timeout", 60.0,
                "Supervisor hang detector: a worker whose heartbeat "
                "file (touched by core.health.beat every step) is older "
                "than this many seconds is declared hung — SIGABRT for "
                "a faulthandler stack dump, then handled per policy.",
                validator=lambda v: v > 0)
    define_flag("ft_max_worker_restarts", 2,
                "Per-rank relaunch budget under ft_supervise=restart; "
                "a rank exceeding it fails the pod (fail_fast).",
                validator=lambda v: v >= 0)
    define_flag("ft_elastic_min_world", 1,
                "Smallest world size an elastic resize may shrink to: "
                "losing enough workers to fall below this fails the pod "
                "instead of limping on (capacity floor for preemptible "
                "fleets).",
                validator=lambda v: v >= 1)
    define_flag("ft_max_resizes", 8,
                "Total world-resize budget per supervised job (shrinks "
                "+ grows + explicit requests); exceeding it fails the "
                "pod — a fleet that resizes forever is churning, not "
                "training.",
                validator=lambda v: v >= 0)
    define_flag("ft_chaos", "",
                "Deterministic failure-injection spec armed by "
                "core.chaos.configure_from_flags (e.g. "
                "'nan_batch@3,ckpt_fail@2,preempt@7'; worker-level "
                "points take an optional rank qualifier: "
                "'worker_kill@5:1' = rank 1's 5th health beat). Empty "
                "disables. Each armed occurrence fires exactly once, so "
                "retried/replayed operations come back clean, and "
                "worker points fire in incarnation 0 only, so a "
                "supervisor-restarted rank replays clean.")
    # Input pipeline resilience (consumed by io.DataLoader /
    # fluid.PyReader and surfaced through ResilienceReport)
    define_flag("loader_bad_sample", "raise",
                "What the input pipeline does when one sample fetch "
                "fails (dataset __getitem__ raises, a reader item "
                "won't convert, or an armed corrupt_sample chaos "
                "point): raise (fail the epoch — today's semantics, "
                "the default), skip (drop the sample, count it), "
                "quarantine (drop + append {index, error, worker} to "
                "the loader's quarantine log and, when "
                "loader_quarantine_file is set, to that JSONL file).",
                validator=lambda v: v in ("raise", "skip", "quarantine"))
    define_flag("loader_max_worker_restarts", 2,
                "Per-worker re-spawn budget when a DataLoader worker "
                "process dies (OOM-kill, segfault) or is restarted by "
                "the input-stall watchdog; a worker exceeding it fails "
                "the epoch with the legacy sticky RuntimeError (or "
                "DataLoaderStalled for a stall).",
                validator=lambda v: v >= 0)
    define_flag("loader_stall_timeout_s", 0.0,
                "Input-stall watchdog: if no batch arrives within this "
                "many seconds the loader dumps worker liveness + the "
                "pending task map, then restarts the stalled worker "
                "(multi-process path, within the restart budget) or "
                "raises DataLoaderStalled. 0 disables (the default — "
                "a legitimately slow first batch must not be killed). "
                "While waiting, the loader calls health.beat() so the "
                "Supervisor doesn't mistake a slow loader for a hung "
                "trainer.",
                validator=lambda v: v >= 0)
    define_flag("loader_chaos_stall_s", 1.0,
                "How long the loader_stall chaos point wedges one "
                "batch/task (must exceed the loader_stall_timeout_s "
                "under test for the watchdog to trip).",
                validator=lambda v: v >= 0)
    define_flag("loader_quarantine_file", "",
                "Optional JSONL file the quarantine policy appends "
                "{index, error, worker} records to (the in-memory "
                "loader.quarantine list is always kept). Empty "
                "disables the file sink.")
    # Serving runtime (consumed by paddle1_tpu.serving; the dynamic
    # micro-batching analog of the reference's inference Config knobs —
    # MIGRATING.md maps EnableMemoryOptim-era toggles onto these)
    define_flag("serve_max_batch", 16,
                "Serving micro-batch ceiling: the Batcher dispatches as "
                "soon as this many request rows are queued (or the "
                "batch timeout fires). Must be covered by the largest "
                "shape bucket.",
                validator=lambda v: v >= 1)
    define_flag("serve_batch_timeout_ms", 5.0,
                "How long the Batcher holds an incomplete micro-batch "
                "open for more requests before dispatching it anyway. "
                "The latency/occupancy tradeoff dial: 0 dispatches "
                "immediately (lowest latency, occupancy 1/bucket).",
                validator=lambda v: v >= 0)
    define_flag("serve_queue_depth", 256,
                "Bound on queued (admitted, not yet dispatched) serving "
                "requests; submissions beyond it are shed with "
                "ServerOverloaded (admission control — an unbounded "
                "queue converts overload into every request blowing "
                "its deadline instead).",
                validator=lambda v: v >= 1)
    define_flag("serve_buckets", "",
                "Comma-separated batch-size buckets the InferenceEngine "
                "compiles (e.g. '1,4,16'); micro-batches pad up to the "
                "smallest covering bucket so the executable count stays "
                "fixed (the serving-side retrace guard). Empty = powers "
                "of two up to serve_max_batch.")
    define_flag("serve_deadline_ms", 0.0,
                "Default per-request deadline: requests still queued "
                "when it expires fail with DeadlineExceeded instead of "
                "occupying a micro-batch (0 disables; submit() can "
                "override per request).",
                validator=lambda v: v >= 0)
    define_flag("serve_chaos_slow_s", 0.25,
                "How long the serve_slow_step chaos point stalls one "
                "micro-batch dispatch — and the replica_slow point one "
                "replica request (tests drive the deadline/shed and "
                "overload-degradation paths with it).",
                validator=lambda v: v >= 0)
    # Serving fleet (consumed by paddle1_tpu.serving.fleet — the
    # multi-replica HA layer over the Server; MIGRATING.md maps the
    # reference Paddle Serving replica/timeout/retry knobs onto these)
    define_flag("serve_replicas", 2,
                "How many replica Server subprocesses a ServingFleet "
                "runs (the reference Paddle Serving '--replica num' "
                "analog). Each replica is a Supervisor-managed worker: "
                "heartbeats, hang detection, restart budgets.",
                validator=lambda v: v >= 1)
    define_flag("serve_retry_max", 2,
                "How many times the fleet re-dispatches one request "
                "onto a different replica after the one holding it "
                "died or wedged (idempotent pure-forward inference "
                "makes the retry safe); exhausting the budget fails "
                "the request with typed ReplicaFailed.",
                validator=lambda v: v >= 0)
    define_flag("serve_replica_timeout_ms", 30000.0,
                "Fleet-side per-request transport deadline: a request "
                "in flight on one replica longer than this is treated "
                "as a wedged replica (circuit-break, restart, retry "
                "elsewhere) — the detector for replicas that hang "
                "while their heartbeat keeps beating.",
                validator=lambda v: v > 0)
    define_flag("serve_breaker_failures", 3,
                "Consecutive unexpected failures (transport timeouts, "
                "engine errors — not client-typed deadlines/sheds) "
                "that trip one replica's circuit breaker: the replica "
                "is drained out of rotation and relaunched.",
                validator=lambda v: v >= 1)
    define_flag("serve_fleet_queue_depth", 512,
                "Bound on fleet-queued (admitted, not yet sent to a "
                "replica) requests; beyond it submissions shed with "
                "ServerOverloaded, and the adaptive-admission EWMA "
                "is measured against it.",
                validator=lambda v: v >= 1)
    define_flag("serve_shed_start", 0.5,
                "Queue-depth EWMA fraction (of serve_fleet_queue_depth) "
                "where adaptive admission starts shedding: overload "
                "ramps 0→1 between this fraction and a full queue, "
                "progressively shedding lowest-priority/longest-"
                "deadline work first so admitted p99 stays bounded.",
                validator=lambda v: 0 < v < 1)
    define_flag("serve_priority_levels", 4,
                "Priority classes for fleet admission (0 = highest, "
                "never adaptively shed; levels-1 = lowest, shed "
                "first under overload).",
                validator=lambda v: v >= 2)
    # Generative serving (consumed by paddle1_tpu.serving.generate —
    # the KV-cached continuous-batching decode engine; MIGRATING.md
    # maps the reference FastGeneration/max_dec_len knobs onto these)
    define_flag("serve_gen_slots", 16,
                "Decode slots in the GenerationEngine's device-resident "
                "KV cache — the continuous-batching degree: one jitted "
                "decode dispatch per token advances up to this many "
                "sequences, and new requests claim slots as finished "
                "ones release theirs. The decode executable is "
                "compiled ONCE for [slots, max_seq]; changing this "
                "recompiles.",
                validator=lambda v: v >= 1)
    define_flag("serve_gen_max_seq", 256,
                "KV-cache sequence capacity per slot (prompt + "
                "generated tokens). Sizes the preallocated per-layer "
                "[slots, max_seq, heads, dim] cache; requests whose "
                "prompt + token budget exceed it are rejected typed at "
                "submit.",
                validator=lambda v: v >= 2)
    define_flag("serve_gen_prefill_buckets", "",
                "Comma-separated prompt-length buckets the prefill "
                "executable compiles (e.g. '16,64,256'); prompts pad "
                "up to the smallest covering bucket, so prefill "
                "compiles stay bounded while decode stays ONE "
                "executable. Empty = powers of two up to "
                "serve_gen_max_seq.")
    define_flag("serve_gen_token_budget", 128,
                "Server-side cap on generated tokens per request: a "
                "stream still running when it exhausts the budget "
                "fails mid-stream with typed DeadlineExceeded (the "
                "client sees a truncation, not silence). Requests may "
                "ask for fewer via max_new_tokens.",
                validator=lambda v: v >= 1)
    define_flag("serve_gen_stream_buffer", 64,
                "Bounded per-stream token buffer (the async_loss "
                "in-flight-window idiom as backpressure): a client not "
                "consuming its TokenStream parks its slot — the slot "
                "stays claimed but stops decoding — until the buffer "
                "drains, instead of growing host memory unboundedly.",
                validator=lambda v: v >= 1)
    # Decode economics (ISSUE 16): block-paged KV cache with prefix
    # sharing, speculative decoding, int8 decode weights — all behind
    # the ONE compiled decode signature (decode_compile_count==1).
    define_flag("serve_gen_paged", False,
                "Block-paged KV cache for the GenerationEngine: K/V "
                "live in a global [pages, page_size, heads, dim] pool "
                "per layer with a per-slot page table, so a short "
                "request holds ceil(len/page_size) pages instead of a "
                "dense max_seq row — HBM scales with live tokens, not "
                "slots*max_seq (the vLLM PagedAttention discipline). "
                "Off = the PR 8 dense slot cache, bit-compatible.")
    define_flag("serve_gen_kv_page_size", 16,
                "Tokens per KV page under serve_gen_paged. Must divide "
                "every prefill bucket (powers of two compose). Smaller "
                "pages waste less tail capacity per request but grow "
                "the page table and the gather fan-out; 16-64 is the "
                "usual sweet spot.",
                validator=lambda v: v >= 1)
    define_flag("serve_gen_kv_pages", 0,
                "Page-pool capacity (pages) under serve_gen_paged; "
                "0 = auto-size to the dense equivalent "
                "(slots * ceil(max_seq/page_size) + 1 parking page). "
                "Size it BELOW auto to serve more slots than dense HBM "
                "would allow — admission waits for pages, and prefix "
                "sharing stretches the pool further.",
                validator=lambda v: v >= 0)
    define_flag("serve_gen_prefix_cache", 64,
                "Prefix-registry entries for copy-on-write prompt "
                "sharing under serve_gen_paged: full pages of a "
                "previously-prefilled prompt prefix are reused by "
                "refcount instead of recomputed/stored again (N "
                "requests over one system prompt hold its pages once)."
                " LRU-evicted under pool pressure. 0 disables sharing.",
                validator=lambda v: v >= 0)
    define_flag("serve_gen_spec_tokens", 0,
                "Speculative-decoding draft length k: each decode "
                "dispatch verifies k speculator-proposed tokens plus "
                "samples one correction, so one dispatch can produce "
                "up to k+1 tokens. Acceptance is by equality against "
                "the engine's own deterministic per-request sample "
                "chain, so output (greedy AND sampled) is bit-"
                "identical to non-speculative decode. 0 = off. Each "
                "slot reserves k scratch rows of seq capacity.",
                validator=lambda v: v >= 0)
    define_flag("serve_gen_spec_ngram", 3,
                "N-gram order of the prompt-lookup speculator: drafts "
                "are the tokens that followed the most recent earlier "
                "occurrence of the last n tokens (falling back to "
                "shorter grams), the zero-model speculator that wins "
                "on repetitive/templated text.",
                validator=lambda v: v >= 1)
    define_flag("serve_gen_int8", False,
                "Per-output-channel int8 weight quantization for the "
                "decode matmuls (quantization.quantize_weights_int8): "
                "Linear weights ride the decode dispatch as int8 + "
                "f32 scales and dequantize inside the trace, cutting "
                "the weight HBM traffic that dominates decode. Lossy "
                "(not bit-parity with f32 decode).")
    define_flag("serve_ready_timeout_s", 120.0,
                "How long the fleet waits for a (re)spawned replica to "
                "publish its endpoint and pass the ready handshake "
                "(covers import + per-bucket XLA warmup) before "
                "treating the launch — or a deploy canary — as failed.",
                validator=lambda v: v > 0)
    # Generation fleet (consumed by paddle1_tpu.serving.genfleet — the
    # multi-replica HA layer over the GenerationServer with bit-
    # identical mid-stream failover; MIGRATING.md maps Paddle Serving
    # HA / FastGeneration deployment habits onto these)
    define_flag("serve_gen_replicas", 2,
                "How many GenerationServer replica subprocesses a "
                "GenerationFleet runs. Each is a Supervisor-managed "
                "worker (heartbeats, hang detection, restart budgets); "
                "a dead or wedged replica's in-flight token streams "
                "are re-admitted on survivors bit-identically.",
                validator=lambda v: v >= 1)
    define_flag("serve_gen_streams_per_replica", 0,
                "Fleet-side cap on concurrently dispatched streams per "
                "gen replica (its routing window). 0 = the replica's "
                "own slot count (serve_gen_slots): the fleet never "
                "queues more streams onto one replica than it can "
                "decode concurrently.",
                validator=lambda v: v >= 0)
    define_flag("serve_gen_stream_timeout_ms", 10000.0,
                "Fleet-side stream-silence deadline: a replica with "
                "live streams that has produced NO token frame for "
                "this long is treated as wedged (heartbeating-but-"
                "stuck) — taken out of rotation, restarted, and its "
                "streams failed over. Long-lived streams make the "
                "per-request transport deadline useless here; silence "
                "is the signal. Must cover one worst-case decode step "
                "plus prefill of the deepest bucket.",
                validator=lambda v: v > 0)
    define_flag("serve_gen_preempt", False,
                "KV-pressure graceful degradation in the generation "
                "scheduler: a decode-time page fault preempts the "
                "lowest-priority / longest-deadline cohabiting stream "
                "(its pages are released the same tick, the request is "
                "parked, then re-admitted via the bit-identical replay "
                "path) instead of failing the faulting stream with "
                "KVPoolExhausted; the prefix cache always sheds LRU "
                "entries before any live stream is touched. Off (the "
                "default) keeps the PR 16 fail-typed behavior.")
    define_flag("serve_gen_pressure_ceiling", 0.95,
                "Occupancy fraction of the KV page pool above which "
                "fleet/scheduler admission defers new prefills (the "
                "queue holds them) under serve_gen_preempt, keeping "
                "headroom so admitted streams' decode growth preempts "
                "or parks instead of ever seeing KVPoolExhausted.",
                validator=lambda v: 0 < v <= 1)
    # Autoscaling + traffic simulation (consumed by
    # paddle1_tpu.serving.autoscale / .traffic and bench.py --traffic
    # — ISSUE 18 closes the control loop the obs_slos sensor feeds)
    define_flag("serve_autoscale", "",
                "Declarative scaling policy for serving.Autoscaler "
                "(parse_policy grammar, ';'-separated): 'min=2;max=8;"
                "queue_hi=0.75;queue_lo=0.2;burn_hi=1.0;burn_lo=0.5;"
                "occ_hi=0.9;occ_lo=0.3;kv_free_min=0;step=1;"
                "cooldown=10;dwell=30;backoff=20;interval=1'. "
                "queue_* bound the admission queue-depth EWMA ratio, "
                "burn_* the worst obs_slos burn rate, occ_* stream-"
                "slot occupancy, kv_free_min the free-KV-page floor "
                "(generative fleets). Scale-out above the _hi bounds, "
                "scale-in only below the _lo bounds after 'dwell' "
                "calm seconds; refused transitions back off 'backoff' "
                "seconds typed. Empty = policy defaults (the loop "
                "still only runs when an Autoscaler is constructed — "
                "no Autoscaler, structurally zero cost).")
    define_flag("serve_traffic", "",
                "Production-day traffic model for serving.traffic "
                "(parse_traffic grammar, ';'-separated): 'rps=40;"
                "dur=30;diurnal=0.3;flash=10x@12+6;tail=1.5;"
                "len=8:512;prio=0:0.7,1:0.2,2:0.1;deadline=250;"
                "seed=7'. Open-loop arrivals (offered load never "
                "slows for a saturated fleet): diurnal sinusoid, "
                "multiplicative flash crowds, Pareto payload-length "
                "tail, weighted priority classes. Empty = model "
                "defaults; bench.py --traffic composes this with "
                "chaos_* points for the autoscaler acceptance run.")
    define_flag("debug_kv_refcount", False,
                "KV page-accounting invariant checker: after every "
                "scheduler tick the PagePool verifies sum-of-refcounts "
                "== refs held by live slots + prefix registry (+ chaos "
                "holds), free-list exactness and duplicate-freedom — "
                "raising typed KVPageAccountingError at the tick that "
                "corrupted accounting, not at the far-away alloc that "
                "trips over it later. Off (the default) is free: one "
                "module-bool test per tick.")
    # Observability (consumed by paddle1_tpu.obs — the unified metrics
    # registry, cross-process tracing and live telemetry of ISSUE 10;
    # MIGRATING.md maps the reference paddle.profiler / tools/timeline
    # knobs onto these)
    define_flag("obs_metrics", False,
                "Per-step training instrumentation into the process "
                "MetricsRegistry (engine phase histograms: data wait, "
                "shard, dispatch, readback; samples/s and "
                "steps-per-readback gauges). Off by default so the "
                "disabled hot-path cost is ~0 (the bench.py --obs "
                "gate); rare lifecycle counters (checkpoints, "
                "restarts, quarantines) record regardless.")
    define_flag("obs_port", 0,
                "Serve GET /metrics (Prometheus text exposition of the "
                "process registry) and /healthz from a stdlib-HTTP "
                "daemon thread on this port. 0 disables (default), -1 "
                "binds an ephemeral port. ServingFleet.start_telemetry "
                "and Supervisor.start_telemetry additionally aggregate "
                "child pages via merge_snapshots.",
                validator=lambda v: v >= -1)
    define_flag("obs_trace_dir", "",
                "Cross-process trace sink: every process appends "
                "completed spans (trace_id/span_id/parent, epoch-us "
                "timestamps) to spans-<pid>.jsonl under this "
                "directory; obs.trace.export_chrome_trace merges them "
                "into one chrome://tracing view with flow arrows "
                "(request: client -> fleet router -> replica -> "
                "batcher -> dispatch; training: per-step phase "
                "breakdown). Propagated to Supervisor workers and "
                "fleet replicas via FLAGS_obs_trace_dir env. Empty "
                "disables.")
    define_flag("obs_flight_steps", 0,
                "Crash flight recorder (obs/flight.py): keep a bounded "
                "ring of the last N step metric snapshots plus recent "
                "spans and lifecycle events, dumped atomically as "
                "flight-<pid>.jsonl on an uncaught exception, on a "
                "preemption/supervisor-drain exit, or on demand via "
                "the telemetry endpoint's GET /debug/flight. 0 (the "
                "default) is structurally free: recorder() returns "
                "None and every tap site is a pointer test. Step "
                "snapshots need obs_metrics on (they ride the "
                "instrumented dispatch).",
                validator=lambda v: v >= 0)
    define_flag("obs_flight_dir", "",
                "Where flight-recorder bundles land; empty falls back "
                "to obs_trace_dir (so export_chrome_trace merges them "
                "onto the span timeline), else the working directory.")
    define_flag("obs_hbm_leak_steps", 0,
                "HBM growth detector (obs/hbm.py): raise typed "
                "HbmLeakSuspected after this many CONSECUTIVE steps "
                "of strictly growing registered device-buffer bytes "
                "(params/opt-state/KV-cache census, fed per "
                "instrumented step under obs_metrics). 0 (the "
                "default) disables — the sanitizer-lane idiom: "
                "structurally free off, deterministic and loud "
                "when armed.",
                validator=lambda v: v >= 0)
    define_flag("obs_slos", "",
                "Declarative SLOs evaluated over the process metrics "
                "registry (obs/slo.py), ';'-separated: "
                "'lat=p99(e2e_ms)<50;err=rate(errors_total/"
                "requests_total)<0.01;fresh=stale(age_seconds)<600'. "
                "Evaluation is pull-driven (a /healthz scrape, a "
                "controller tick): each objective publishes "
                "slo_<name>_burn_rate_ratio / slo_<name>_ok gauges "
                "and the /healthz document gains the verdicts — the "
                "sensor layer the ROADMAP #4 autoscaler reads. Empty "
                "disables.")
    define_flag("obs_events_file", "",
                "Structured JSONL lifecycle journal (restart, resize, "
                "deploy, shed, quarantine, checkpoint commit): one "
                "JSON object per line, shared append-safely by every "
                "process of a job (propagated to workers via env). "
                "Empty disables.")
    # IO formats
    define_flag("io_load_pickle", False,
                "Allow fluid.io load_* to read LEGACY pickle payloads. "
                "Off by default: pickle executes arbitrary code from an "
                "untrusted checkpoint, and serving loads untrusted "
                "artifacts — the current save_* format is np.savez "
                "(non-executable). Enable only for trusted pre-PR-4 "
                "files, then re-save.")
    define_flag("conv_nhwc", "auto",
                "Run NCHW-API image ops (2-D conv with HWIO weights, "
                "max/avg pool, batch norm) internally channels-last, "
                "transposing at each op boundary so XLA cancels the "
                "interior transpose pairs. The r5 on-chip probes showed "
                "the axon backend does no layout assignment of its own: "
                "NHWC+HWIO convs sustain ~100 TF/s while NCHW convs and "
                "NCHW reduce_window pooling are 20-100x slower "
                "(chip_results/conv_probe2.txt). Values: auto (TPU "
                "only), always, never; tools/tpu_conv_probe.py measures "
                "both layouts.",
                validator=lambda v: v in ("auto", "always", "never"))


_define_builtin_flags()
