"""Worker-side health channel for the elastic supervisor.

The :class:`~paddle1_tpu.distributed.supervisor.Supervisor` owns worker
subprocesses and needs three signals a plain ``Popen.poll()`` cannot
give: *liveness* (a worker that is alive but wedged in a deadlocked
queue or stuck collective polls as healthy forever), *self-reported
health* (a worker that knows it is broken before it crashes), and a
*stack dump* channel for diagnosing a hang post-mortem. This module is
the worker half of that contract; it is deliberately dependency-light
(stdlib + an optional lazy chaos import) so a supervised worker can
speak the protocol before — or without — importing the full package.

Protocol (all via environment variables stamped by the Supervisor):

``PADDLE_FT_HEARTBEAT_FILE``
    Per-rank heartbeat file. :func:`beat` touches it (mtime is the
    signal); the supervisor declares a hang when the age exceeds
    ``ft_hang_timeout``. Workers call :func:`beat` once per training
    step — it is a no-op (one env lookup) when unsupervised, and
    rate-limited to at most one ``utime`` per ``_MIN_BEAT_INTERVAL_S``
    when supervised.
``PADDLE_FT_STACKDUMP_FILE``
    Where ``faulthandler`` writes the all-threads traceback when the
    supervisor sends ``SIGABRT`` to a hung worker (registered on first
    :func:`beat`; registration replaces the default abort so the
    supervisor can still SIGKILL afterwards).
``PADDLE_FT_WORKER_INCARNATION``
    0 for the first launch, incremented per restart. Worker-level chaos
    points (``worker_kill``/``worker_hang``/``worker_unhealthy``) fire
    only in incarnation 0, so a restarted worker replays clean — the
    same fire-once contract as every other chaos point.

First :func:`beat` also installs a ``SIGTERM`` handler that calls
:func:`~paddle1_tpu.core.chaos.request_preemption` and marks a drain
request, so a supervisor ``drain`` (or a real preemption SIGTERM)
unwinds through the resilient loop's graceful-checkpoint path instead
of killing mid-step. The env vars are removed from ``os.environ`` at
install time: grandchild processes (e.g. ProcessMultiTrainer workers
forwarding ``PADDLE_*``) must not adopt their parent's heartbeat file
or signal handlers.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

try:  # standalone import (tests load this file directly) lacks a package
    from . import chaos as _chaos
except ImportError:  # pragma: no cover - only hit outside the package
    _chaos = None

__all__ = ["beat", "supervised", "incarnation", "report_unhealthy",
           "request_drain", "drain_requested", "add_drain_callback",
           "remove_drain_callback", "reset",
           "HEARTBEAT_ENV", "STACKDUMP_ENV", "INCARNATION_ENV",
           "UNHEALTHY_SUFFIX"]

HEARTBEAT_ENV = "PADDLE_FT_HEARTBEAT_FILE"
STACKDUMP_ENV = "PADDLE_FT_STACKDUMP_FILE"
INCARNATION_ENV = "PADDLE_FT_WORKER_INCARNATION"
# the unhealthy marker sits next to the heartbeat file: one env var
# carries the whole channel
UNHEALTHY_SUFFIX = ".unhealthy"

_MIN_BEAT_INTERVAL_S = 0.05

_lock = threading.Lock()
_installed = False
_hb_file: Optional[str] = None
_incarnation = 0
_last_beat = 0.0
_beats = 0
_drain = False
_dump_fh = None  # keep the faulthandler file object alive
_prev_sigterm = None  # the script's own handler, chained by _on_sigterm
# drain subscribers (serving.Server registers one): each must be
# signal-handler safe — set a flag/Event, never do work
_drain_callbacks: list = []


def _install_from_env() -> None:
    """One-time adoption of the supervisor's env protocol (idempotent;
    called under ``_lock``)."""
    global _installed, _hb_file, _incarnation, _dump_fh
    _installed = True
    _hb_file = os.environ.pop(HEARTBEAT_ENV, None)
    if _hb_file is None:
        return
    _incarnation = int(os.environ.pop(INCARNATION_ENV, "0") or 0)
    dump_path = os.environ.pop(STACKDUMP_ENV, None)
    if dump_path:
        try:
            import faulthandler
            _dump_fh = open(dump_path, "w")
            # enable (register() refuses SIGABRT — it is one of
            # faulthandler's own fatal signals): the supervisor's
            # SIGABRT makes the wedged worker dump all threads to the
            # per-rank file and die; the supervisor reads the dump,
            # then SIGKILLs any straggler
            faulthandler.enable(file=_dump_fh, all_threads=True)
        except (OSError, ValueError, AttributeError) as e:
            print(f"health: stack-dump channel disabled ({e})",
                  file=sys.stderr)
    if threading.current_thread() is threading.main_thread():
        try:
            global _prev_sigterm
            prev = signal.signal(signal.SIGTERM, _on_sigterm)
            if prev is not _on_sigterm:
                # keep the EARLIEST real handler: a reset()+reinstall
                # must not capture our own handler as "previous" (the
                # chain would recurse into itself on the drain SIGTERM)
                _prev_sigterm = prev
        except (OSError, ValueError) as e:  # pragma: no cover
            print(f"health: SIGTERM drain handler not installed ({e})",
                  file=sys.stderr)


def _on_sigterm(signum, frame):
    """Supervisor drain (or a real preemption notice): request a
    graceful stop. Signal-handler safe: sets two flags, then chains to
    the script's own pre-existing SIGTERM handler (its cleanup must
    still run)."""
    global _drain
    _drain = True
    if _chaos is not None:
        _chaos.request_preemption()
    _notify_drain()
    if callable(_prev_sigterm):
        _prev_sigterm(signum, frame)


def _notify_drain() -> None:
    """Run registered drain subscribers (signal-context safe: they only
    set flags). A failing subscriber must not block the others or the
    chained handler."""
    for cb in list(_drain_callbacks):
        try:
            cb()
        except Exception:
            pass


def supervised() -> bool:
    """Whether this process runs under a Supervisor heartbeat channel."""
    with _lock:
        if not _installed:
            _install_from_env()
        return _hb_file is not None


def incarnation() -> int:
    """This worker's restart incarnation under its Supervisor (0 for
    the first launch, +1 per relaunch; 0 when unsupervised). Chaos
    arming gates on it: worker/replica points fire in incarnation 0
    only, so a restarted life replays clean."""
    with _lock:
        if not _installed:
            _install_from_env()
        return _incarnation


def beat() -> None:
    """Touch the per-rank heartbeat file (the liveness signal). Called
    once per training step by ``ResilientTrainer.fit`` and
    ``fleet/process_trainer._worker_main``; cheap no-op when the process
    is not supervised. Also the worker-level chaos trigger point."""
    global _last_beat, _beats
    with _lock:
        if not _installed:
            _install_from_env()
        if _hb_file is None:
            return
        _beats += 1
        beats = _beats
        now = time.monotonic()
        if now - _last_beat >= _MIN_BEAT_INTERVAL_S:
            _last_beat = now
            try:
                with open(_hb_file, "a"):
                    os.utime(_hb_file, None)
            except OSError:  # hb dir vanished (teardown race): not fatal
                pass
    _check_worker_chaos(beats)


def _check_worker_chaos(beats: int) -> None:
    """Fire armed worker-level chaos on this beat. Incarnation 0 only:
    a restarted worker must replay clean (the fire-once contract)."""
    if _chaos is None or _incarnation != 0 or not _chaos.enabled():
        return
    action = _chaos.check_worker(_rank())
    if action is None:
        return
    if action == _chaos.WORKER_KILL:
        # an ungraceful death: no cleanup, no atexit — SIGKILL self
        os.kill(os.getpid(), signal.SIGKILL)
    elif action == _chaos.WORKER_HANG:
        # a wedge: stop beating and block forever (the supervisor's
        # hang detector + SIGABRT dump + SIGKILL is the only way out)
        while True:  # pragma: no cover - exits only via SIGKILL
            time.sleep(3600)
    elif action == _chaos.WORKER_UNHEALTHY:
        report_unhealthy("chaos: injected unhealthy report "
                         f"(beat {beats})")


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:  # pragma: no cover
        return 0


def report_unhealthy(reason: str) -> None:
    """Explicitly tell the supervisor this worker is unhealthy (it keeps
    running; the supervisor responds per policy). No-op when
    unsupervised."""
    with _lock:
        if not _installed:
            _install_from_env()
        if _hb_file is None:
            return
        try:
            with open(_hb_file + UNHEALTHY_SUFFIX, "w") as f:
                f.write(reason)
        except OSError:  # pragma: no cover
            pass


def request_drain() -> None:
    """Programmatic equivalent of the supervisor's drain SIGTERM:
    checkpoint at the next opportunity, then stop."""
    global _drain
    _drain = True
    if _chaos is not None:
        _chaos.request_preemption()
    _notify_drain()


def add_drain_callback(cb) -> None:
    """Subscribe to drain requests (SIGTERM under supervision, or
    :func:`request_drain`). The callback may fire from a signal handler:
    it must only set a flag/Event. Duplicate registrations are
    collapsed; unsubscribe with :func:`remove_drain_callback` (a
    long-lived process creating servers per model reload must not
    accumulate dead subscribers); ``reset()`` clears the list."""
    with _lock:
        if cb not in _drain_callbacks:
            _drain_callbacks.append(cb)


def remove_drain_callback(cb) -> None:
    """Unsubscribe a drain callback (no-op if not registered)."""
    with _lock:
        try:
            _drain_callbacks.remove(cb)
        except ValueError:
            pass


def drain_requested() -> bool:
    """Whether a graceful stop was requested (drain SIGTERM or
    :func:`request_drain`). Checked by ``ResilientTrainer.fit`` after
    its graceful-preemption checkpoint."""
    return _drain


def reset() -> None:
    """Forget the installed channel (test isolation). Does not undo the
    SIGTERM/faulthandler registration."""
    global _installed, _hb_file, _incarnation, _last_beat, _beats, _drain
    with _lock:
        _installed = False
        _hb_file = None
        _incarnation = 0
        _last_beat = 0.0
        _beats = 0
        _drain = False
        _drain_callbacks.clear()
