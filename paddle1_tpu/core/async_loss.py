"""Lazy loss handles — defer the device→host readback until the value is
actually formatted.

The bench honesty contract (bench.py header) measured ~70 ms per
device→host round trip through the axon tunnel; a training loop that
calls ``float(loss.item())`` every batch is therefore bounded by the
host, not by XLA. :class:`LossFuture` keeps the loss as a device array
and only fetches it to host memory when someone *reads* it — ``float()``,
``.item()``, ``np.asarray`` (``__array__``), or string formatting. Until
then the only cost is the handle itself; XLA's async dispatch runs ahead.

``block()`` is the cheap synchronization point: it waits for the device
computation WITHOUT copying the value to host (no readback). The engine
and ``hapi.Model.fit`` use it to bound the in-flight dispatch window.

A module-level readback counter is the test hook for the "no per-batch
readback" acceptance criterion: every actual device→host materialization
increments it exactly once per handle (the fetched value is cached).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import numpy as np

__all__ = ["LossFuture", "StepFuture", "readback_count",
           "reset_readback_count", "set_readback_observer"]

_lock = threading.Lock()
_readbacks = 0
# optional duration hook (seconds per materialization): obs wires the
# train_readback_seconds histogram through it when obs_metrics is on —
# None (the default) keeps the fetch path free of even a perf_counter
_observer: Optional[Callable[[float], None]] = None


def set_readback_observer(fn: Optional[Callable[[float], None]]) -> None:
    """Install (or clear, with None) a callable receiving each
    materialization's duration in seconds."""
    global _observer
    _observer = fn


def readback_count() -> int:
    """Total LossFuture device→host materializations (test hook)."""
    return _readbacks


def reset_readback_count() -> None:
    global _readbacks
    with _lock:
        _readbacks = 0


def _count_readback() -> None:
    global _readbacks
    with _lock:
        _readbacks += 1
    # the jit sanitizer's host-sync accounting (ISSUE 12): one module
    # bool test when the sanitizer never armed — attribution to the
    # engine step loop (or whatever hot_section the thread is in)
    # makes "this loop pays one readback per chunk" assertable
    from . import jit_sanitizer
    jit_sanitizer.note_host_sync("loss_readback")


class LossFuture:
    """A loss value still living on device. Reads materialize it.

    Wraps a jax array (or Tensor); scalar losses behave like a float
    wherever one is expected (``float()``, ``f"{loss:.4f}"``, numpy
    coercion). ``step_many`` returns one future over the whole ``[k]``
    loss vector — ``np.asarray(fut)`` yields the k losses in one
    readback.
    """

    __slots__ = ("_arr", "_result")

    def __init__(self, value: Any):
        # Tensor → its backing array; plain floats/np pass through and
        # materialize for free.
        self._arr = value.data if hasattr(value, "data") else value
        self._result: Optional[np.ndarray] = None

    # -- device-side ------------------------------------------------------

    @property
    def data(self):
        """The underlying (device) array — no readback."""
        return self._arr

    def block(self) -> "LossFuture":
        """Wait for the device computation to finish WITHOUT fetching the
        value to host (bounds in-flight dispatch; not a readback)."""
        if self._result is None:
            try:
                import jax
                jax.block_until_ready(self._arr)
            except (ImportError, TypeError):
                pass
        return self

    @property
    def materialized(self) -> bool:
        return self._result is not None

    # -- host-side reads (each handle reads back at most once) -------------

    def numpy(self) -> np.ndarray:
        if self._result is None:
            obs = _observer
            t0 = time.perf_counter() if obs is not None else 0.0
            self._result = np.asarray(self._arr)
            _count_readback()
            if obs is not None:
                obs(time.perf_counter() - t0)
        return self._result

    def item(self) -> float:
        return float(np.ravel(self.numpy())[0]) if self.numpy().size == 1 \
            else self.numpy().item()

    def __float__(self) -> float:
        return self.item()

    def __int__(self) -> int:
        return int(self.item())

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    # Minimal numeric protocol so code written against the old float
    # returns (`if loss < best:`, `total += loss`, `min(losses)`) keeps
    # working — each coerces through item()/numpy(), i.e. materializes.

    def __lt__(self, other):
        return self.item() < other

    def __le__(self, other):
        return self.item() <= other

    def __gt__(self, other):
        return self.item() > other

    def __ge__(self, other):
        return self.item() >= other

    def __eq__(self, other):
        if isinstance(other, LossFuture):
            other = other.item()
        return self.item() == other

    def __ne__(self, other):
        return not self.__eq__(other)

    __hash__ = object.__hash__

    def __add__(self, other):
        return self.item() + other

    __radd__ = __add__

    def __sub__(self, other):
        return self.item() - other

    def __rsub__(self, other):
        return other - self.item()

    def __mul__(self, other):
        return self.item() * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self.item() / other

    def __rtruediv__(self, other):
        return other / self.item()

    def __neg__(self):
        return -self.item()

    def __abs__(self):
        return abs(self.item())

    def __format__(self, spec: str) -> str:
        a = self.numpy()
        if a.size == 1:
            return format(float(np.ravel(a)[0]), spec)
        return format(a, spec)

    def __repr__(self) -> str:
        if self._result is not None:
            return f"LossFuture({self._result!r})"
        return "LossFuture(<pending on device>)"

    def __len__(self):
        return len(self.numpy())

    def __iter__(self):
        return iter(self.numpy())


class StepFuture(LossFuture):
    """A LossFuture over a *packed* ``[..., 2]`` array of
    ``[loss, notfinite]`` pairs — the output of a ``check_finite``
    compiled train step.

    The bad-step flag is computed on device inside the step executable
    and packed next to the loss, so NaN/Inf detection costs no extra
    readback: one host fetch materializes both (and the readback counter
    increments once, same as a plain loss). All the float/format/numpy
    protocol of :class:`LossFuture` sees only the loss column —
    ``float(engine.step(b))`` behaves exactly as without detection —
    while :meth:`bad`, :meth:`bad_count` and :meth:`bad_mask` expose the
    flag side.
    """

    __slots__ = ("_raw",)

    def __init__(self, packed: Any):
        super().__init__(packed)
        self._raw: Optional[np.ndarray] = None

    def _fetch(self) -> np.ndarray:
        if self._raw is None:
            obs = _observer
            t0 = time.perf_counter() if obs is not None else 0.0
            self._raw = np.asarray(self._arr)
            _count_readback()
            if obs is not None:
                obs(time.perf_counter() - t0)
        return self._raw

    def numpy(self) -> np.ndarray:
        if self._result is None:
            self._result = np.asarray(self._fetch()[..., 0])
        return self._result

    def bad_mask(self) -> np.ndarray:
        """Per-step non-finite flags (bool; scalar for a single step,
        ``[k]`` for a ``step_many`` chunk)."""
        return np.asarray(self._fetch()[..., 1] > 0)

    def bad_count(self) -> int:
        return int(np.sum(self.bad_mask()))

    @property
    def bad(self) -> bool:
        """True when any step in this dispatch saw a non-finite loss or
        gradient (the update was skipped on device for those steps)."""
        return bool(np.any(self.bad_mask()))
