"""Typed error hierarchy + enforce macros.

TPU-native analog of the reference's PADDLE_ENFORCE machinery
(/root/reference/paddle/fluid/platform/enforce.h, errors at
platform/errors.h). Python tracebacks replace the C++ demangled stack dumps;
the typed hierarchy is preserved so user code can catch specific categories.
"""

from __future__ import annotations

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
    "ResourceExhaustedError", "PreconditionNotMetError", "UnimplementedError",
    "UnavailableError", "FatalError", "ExecutionTimeoutError",
    "enforce", "enforce_eq", "enforce_gt", "enforce_not_none",
]


class EnforceNotMet(RuntimeError):
    """Base for all framework-raised errors (reference enforce.h:EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, msg="", error_cls=PreconditionNotMetError):
    if not cond:
        raise error_cls(msg if msg else "Enforce condition failed")


def enforce_eq(a, b, msg="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"{msg} (expected {a!r} == {b!r})")


def enforce_gt(a, b, msg="", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"{msg} (expected {a!r} > {b!r})")


def enforce_not_none(x, msg="", error_cls=NotFoundError):
    if x is None:
        raise error_cls(msg if msg else "Expected a non-None value")
    return x
