"""Dtype system.

Analog of the reference's proto::VarType dtype enum + transfer logic
(/root/reference/paddle/fluid/framework/framework.proto, data_type.h).
On TPU the canonical compute dtypes are float32 and bfloat16 (MXU-native);
float16 is supported for API parity but bfloat16 is preferred.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # ships with jax

from .errors import InvalidArgumentError

__all__ = [
    "dtype", "convert_dtype", "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64", "uint8", "bool_", "complex64",
    "complex128", "is_floating", "is_integer", "promote_types",
    "set_default_dtype", "get_default_dtype",
]

# Canonical dtype objects are numpy dtypes (what jax uses internally).
float32 = np.dtype("float32")
float64 = np.dtype("float64")
float16 = np.dtype("float16")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

dtype = np.dtype  # user-facing alias: paddle1_tpu.dtype("float32")

_ALIASES = {
    "float": float32, "double": float64, "half": float16, "bf16": bfloat16,
    "bfloat16": bfloat16, "float32": float32, "float64": float64,
    "float16": float16, "int8": int8, "int16": int16, "int32": int32,
    "int64": int64, "uint8": uint8, "bool": bool_, "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = float32


def set_default_dtype(d) -> None:
    global _default_dtype
    d = convert_dtype(d)
    if not is_floating(d):
        raise InvalidArgumentError(
            f"default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def convert_dtype(d) -> np.dtype:
    """Normalize str/np.dtype/jnp dtype/python type to a numpy dtype."""
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        if d in _ALIASES:
            return _ALIASES[d]
        try:
            return np.dtype(d)
        except TypeError:
            raise InvalidArgumentError(f"Unknown dtype: {d!r}") from None
    if d is float:
        return _default_dtype
    if d is int:
        return int64
    if d is bool:
        return bool_
    try:
        return np.dtype(d)
    except TypeError:
        raise InvalidArgumentError(f"Unknown dtype: {d!r}") from None


def is_floating(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.integer)


def promote_types(a, b) -> np.dtype:
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))
