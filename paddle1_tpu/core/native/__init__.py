"""Native (C++) host runtime: blocking prefetch queue, shared-memory arena,
stats registry. See src/native.cc for the component map to the reference.

The library builds on first import (g++, ~1s, cached next to the source);
every consumer has a pure-Python fallback so the framework degrades
gracefully if no compiler is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["available", "BoundedQueue", "ShmArena", "stat_add", "stat_set",
           "stat_get", "stat_dump"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "native.cc")
_LIB_PATH = os.path.join(_HERE, "libpaddle1_native.so")
_CAPI_SRC = os.path.join(_HERE, "src", "capi.cc")
_CAPI_LIB = os.path.join(_HERE, "libpaddle1_capi.so")
_lib = None
_build_lock = threading.Lock()


def build_capi():
    """Build the C inference ABI (src/capi.cc → libpaddle1_capi.so):
    embedded-interpreter predictor for C/Go deployments (the reference's
    inference/capi analog). Returns the .so path or None."""
    import sysconfig
    with _build_lock:
        if os.path.exists(_CAPI_LIB) and (
                not os.path.exists(_CAPI_SRC) or
                os.path.getmtime(_CAPI_LIB) >= os.path.getmtime(_CAPI_SRC)):
            return _CAPI_LIB  # prebuilt .so shipped without src/
        if not os.path.exists(_CAPI_SRC):
            return None
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
        pyver = f"python{sysconfig.get_config_var('py_version_short')}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               _CAPI_SRC, "-o", _CAPI_LIB, f"-I{inc}", f"-L{libdir}",
               f"-l{pyver}", "-ldl", "-lm"]
        try:
            subprocess.run(cmd, check=True,  # noqa: lock-blocking — serializes the one-shot build
                           capture_output=True,
                           timeout=180)
            return _CAPI_LIB
        except Exception:
            return None


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _LIB_PATH, "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        # signatures
        lib.pq_create.restype = ctypes.c_void_p
        lib.pq_create.argtypes = [ctypes.c_size_t]
        lib.pq_destroy.argtypes = [ctypes.c_void_p]
        lib.pq_put.restype = ctypes.c_int
        lib.pq_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_size_t, ctypes.c_int64]
        lib.pq_get.restype = ctypes.c_void_p
        lib.pq_get.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pq_size.restype = ctypes.c_size_t
        lib.pq_size.argtypes = [ctypes.c_void_p]
        lib.pq_close.argtypes = [ctypes.c_void_p]
        lib.buf_data.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.buf_data.argtypes = [ctypes.c_void_p]
        lib.buf_len.restype = ctypes.c_size_t
        lib.buf_len.argtypes = [ctypes.c_void_p]
        lib.buf_free.argtypes = [ctypes.c_void_p]
        lib.shm_arena_create.restype = ctypes.c_void_p
        lib.shm_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_arena_attach.restype = ctypes.c_void_p
        lib.shm_arena_attach.argtypes = [ctypes.c_char_p]
        lib.shm_arena_detach.argtypes = [ctypes.c_void_p]
        lib.shm_arena_unlink.argtypes = [ctypes.c_char_p]
        lib.shm_alloc.restype = ctypes.c_uint64
        lib.shm_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_ptr.restype = ctypes.c_void_p
        lib.shm_ptr.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_incref.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_decref.restype = ctypes.c_int64
        lib.shm_decref.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_reset.argtypes = [ctypes.c_void_p]
        lib.shm_arena_used.restype = ctypes.c_uint64
        lib.shm_arena_used.argtypes = [ctypes.c_void_p]
        lib.shm_arena_size.restype = ctypes.c_uint64
        lib.shm_arena_size.argtypes = [ctypes.c_void_p]
        lib.stat_add.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.stat_set.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.stat_get.restype = ctypes.c_int64
        lib.stat_get.argtypes = [ctypes.c_char_p]
        lib.stat_dump.restype = ctypes.c_int64
        lib.stat_dump.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int64]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# BoundedQueue — GIL-free blocking queue of pickled payloads.
# ---------------------------------------------------------------------------


class BoundedQueue:
    """Blocking byte-payload queue backed by the C++ MPMC queue; falls back
    to queue.Queue when the native lib is unavailable."""

    def __init__(self, capacity: int = 8):
        lib = _load()
        self._lib = lib
        if lib is not None:
            self._h = lib.pq_create(capacity)
            self._q = None
        else:
            import queue
            self._h = None
            self._q = queue.Queue(maxsize=capacity)

    def put(self, payload: bytes, timeout_ms: int = -1) -> bool:
        if self._lib is not None:
            rc = self._lib.pq_put(self._h, payload, len(payload), timeout_ms)
            if rc == -1:
                raise RuntimeError("queue closed")
            return rc == 0
        self._q.put(payload,
                    timeout=None if timeout_ms < 0 else timeout_ms / 1e3)
        return True

    def get(self, timeout_ms: int = -1):
        if self._lib is not None:
            h = self._lib.pq_get(self._h, timeout_ms)
            if not h:
                return None
            try:
                n = self._lib.buf_len(h)
                data = ctypes.string_at(self._lib.buf_data(h), n)
            finally:
                self._lib.buf_free(h)
            return data
        try:
            return self._q.get(
                timeout=None if timeout_ms < 0 else timeout_ms / 1e3)
        except Exception:
            return None

    def qsize(self) -> int:
        if self._lib is not None:
            return int(self._lib.pq_size(self._h))
        return self._q.qsize()

    def close(self):
        if self._lib is not None and self._h:
            self._lib.pq_close(self._h)

    def __del__(self):
        try:
            if self._lib is not None and self._h:
                self._lib.pq_close(self._h)
                self._lib.pq_destroy(self._h)
                self._h = None
        except Exception:
            pass


# ---------------------------------------------------------------------------
# ShmArena — zero-copy multiprocess tensor transfer.
# ---------------------------------------------------------------------------


class ShmArena:
    """Named shared-memory arena; numpy arrays move between processes as
    (offset, shape, dtype) descriptors (reference mmap_allocator.cc)."""

    def __init__(self, name: str, size: int = 1 << 28, create: bool = True):
        import numpy as np
        self._np = np
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.name = name.encode() if isinstance(name, str) else name
        if create:
            self._base = lib.shm_arena_create(self.name, size)
        else:
            self._base = lib.shm_arena_attach(self.name)
        if not self._base:
            raise RuntimeError(f"shm arena {name!r} mmap failed")
        # the creator's header is authoritative (attachers must not trust
        # their local default)
        self.size = int(lib.shm_arena_size(self._base))

    def put_array(self, arr) -> tuple:
        np = self._np
        arr = np.ascontiguousarray(arr)
        off = self._lib.shm_alloc(self._base, arr.nbytes)
        if off == 0:
            raise MemoryError("shm arena full")
        ctypes.memmove(self._lib.shm_ptr(self._base, off),
                       arr.ctypes.data, arr.nbytes)
        return (off, arr.shape, arr.dtype.str)

    def get_array(self, desc):
        np = self._np
        off, shape, dtype = desc
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        ptr = self._lib.shm_ptr(self._base, off)
        buf = (ctypes.c_uint8 * n).from_address(ptr)
        return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()

    def incref(self, desc):
        """Extra reader share of a block (multi-consumer broadcast)."""
        self._lib.shm_incref(self._base, desc[0])

    def decref(self, desc):
        self._lib.shm_decref(self._base, desc[0])

    def reset(self):
        self._lib.shm_arena_reset(self._base)

    def used(self) -> int:
        return int(self._lib.shm_arena_used(self._base))

    def close(self, unlink: bool = False):
        if self._base:
            self._lib.shm_arena_detach(self._base)
            self._base = None
        if unlink:
            self._lib.shm_arena_unlink(self.name)


# ---------------------------------------------------------------------------
# Stats (monitor.h gauges)
# ---------------------------------------------------------------------------

_py_stats = {}
_py_stats_lock = threading.Lock()


def stat_add(name: str, v: int):
    lib = _load()
    if lib is not None:
        lib.stat_add(name.encode(), int(v))
    else:
        with _py_stats_lock:
            _py_stats[name] = _py_stats.get(name, 0) + int(v)


def stat_set(name: str, v: int):
    lib = _load()
    if lib is not None:
        lib.stat_set(name.encode(), int(v))
    else:
        with _py_stats_lock:
            _py_stats[name] = int(v)


def stat_get(name: str) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.stat_get(name.encode()))
    with _py_stats_lock:
        return _py_stats.get(name, 0)


def stat_dump() -> dict:
    lib = _load()
    if lib is None:
        with _py_stats_lock:
            return dict(_py_stats)
    cap = 1 << 16
    names = ctypes.create_string_buffer(cap)
    vals = (ctypes.c_int64 * 1024)()
    n = lib.stat_dump(names, cap, vals, 1024)
    keys = names.value.decode().split("\n")[:n]
    return dict(zip(keys, vals[:n]))
