// paddle1_tpu native runtime — C++ host-side components.
//
// TPU-native analogs of the reference's C++ runtime pieces that XLA does
// NOT subsume (SURVEY §2.1):
//   * BoundedQueue  — the BufferedReader/blocking-queue substrate
//     (reference paddle/fluid/operators/reader/buffered_reader.h:36,
//     reader/blocking_queue.h): producer threads stage ready host batches
//     while the accelerator consumes, without holding the Python GIL.
//   * ShmArena      — multiprocess DataLoader shared memory
//     (reference paddle/fluid/memory/allocation/mmap_allocator.cc): POSIX
//     shm slabs with a bump/free-list allocator and cross-process
//     refcounts, so worker → parent tensor transfer is zero-copy.
//   * StatRegistry  — named global gauges
//     (reference paddle/fluid/platform/monitor.h:77 StatRegistry/STAT_ADD).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// BoundedQueue: MPMC blocking queue of opaque byte buffers.
// ---------------------------------------------------------------------------

struct Buffer {
  std::vector<uint8_t> data;
};

struct BoundedQueue {
  explicit BoundedQueue(size_t cap) : capacity(cap) {}
  size_t capacity;
  std::deque<Buffer*> items;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  bool closed = false;
};

void* pq_create(size_t capacity) { return new BoundedQueue(capacity); }

void pq_destroy(void* q) {
  auto* bq = static_cast<BoundedQueue*>(q);
  std::lock_guard<std::mutex> g(bq->mu);
  for (auto* b : bq->items) delete b;
  bq->items.clear();
  // note: destruction with blocked waiters is a caller bug; close first.
  delete bq;
}

// Returns 0 on success, -1 if closed. Blocks while full.
int pq_put(void* q, const uint8_t* data, size_t len, int64_t timeout_ms) {
  auto* bq = static_cast<BoundedQueue*>(q);
  std::unique_lock<std::mutex> lk(bq->mu);
  auto pred = [&] { return bq->closed || bq->items.size() < bq->capacity; };
  if (timeout_ms < 0) {
    bq->not_full.wait(lk, pred);
  } else if (!bq->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -2;  // timeout
  }
  if (bq->closed) return -1;
  auto* buf = new Buffer();
  buf->data.assign(data, data + len);
  bq->items.push_back(buf);
  bq->not_empty.notify_one();
  return 0;
}

// Blocks while empty. Returns buffer handle or nullptr if closed+drained.
void* pq_get(void* q, int64_t timeout_ms) {
  auto* bq = static_cast<BoundedQueue*>(q);
  std::unique_lock<std::mutex> lk(bq->mu);
  auto pred = [&] { return bq->closed || !bq->items.empty(); };
  if (timeout_ms < 0) {
    bq->not_empty.wait(lk, pred);
  } else if (!bq->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                     pred)) {
    return nullptr;
  }
  if (bq->items.empty()) return nullptr;  // closed & drained
  auto* buf = bq->items.front();
  bq->items.pop_front();
  bq->not_full.notify_one();
  return buf;
}

size_t pq_size(void* q) {
  auto* bq = static_cast<BoundedQueue*>(q);
  std::lock_guard<std::mutex> g(bq->mu);
  return bq->items.size();
}

void pq_close(void* q) {
  auto* bq = static_cast<BoundedQueue*>(q);
  std::lock_guard<std::mutex> g(bq->mu);
  bq->closed = true;
  bq->not_empty.notify_all();
  bq->not_full.notify_all();
}

const uint8_t* buf_data(void* b) {
  return static_cast<Buffer*>(b)->data.data();
}
size_t buf_len(void* b) { return static_cast<Buffer*>(b)->data.size(); }
void buf_free(void* b) { delete static_cast<Buffer*>(b); }

// ---------------------------------------------------------------------------
// ShmArena: POSIX shared-memory slab with block allocator + refcounts.
// Layout: [ArenaHeader][BlockHeader data...]*
// ---------------------------------------------------------------------------

struct ArenaHeader {
  uint64_t magic;           // 0x50311A7E
  uint64_t size;            // total bytes
  std::atomic<uint64_t> bump;  // offset of next free byte
};

struct BlockHeader {
  uint64_t len;             // payload bytes
  std::atomic<int64_t> refs;
};

static const uint64_t kMagic = 0x50311A7EULL;

// Create (or attach to) a named shm arena; returns mapped base or null.
// ftruncate runs ONLY on fresh O_EXCL creation — resizing an arena another
// process already mapped would shear its mapping (ADVICE r1 finding).
void* shm_arena_create(const char* name, uint64_t size) {
  bool created = true;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0 && errno == EEXIST) {
    created = false;
    fd = shm_open(name, O_RDWR, 0600);
  }
  if (fd < 0) return nullptr;
  if (created) {
    if (ftruncate(fd, (off_t)size) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < size) {
      close(fd);
      return nullptr;  // existing arena too small; caller picks a new name
    }
    size = (uint64_t)st.st_size;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<ArenaHeader*>(base);
  if (created || hdr->magic != kMagic) {
    hdr->magic = kMagic;
    hdr->size = size;
    hdr->bump.store(sizeof(ArenaHeader));
  }
  return base;
}

void* shm_arena_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return base == MAP_FAILED ? nullptr : base;
}

void shm_arena_detach(void* base) {
  auto* hdr = static_cast<ArenaHeader*>(base);
  munmap(base, hdr->size);
}

uint64_t shm_arena_size(void* base) {
  return static_cast<ArenaHeader*>(base)->size;
}

void shm_arena_unlink(const char* name) { shm_unlink(name); }

// Allocate a refcounted block; returns offset of the payload (0 on failure).
uint64_t shm_alloc(void* base, uint64_t len) {
  auto* hdr = static_cast<ArenaHeader*>(base);
  uint64_t need = sizeof(BlockHeader) + ((len + 63) & ~63ULL);
  // CAS loop instead of fetch_add + rollback: a failed add followed by a
  // fetch_sub can momentarily overlap a concurrent winner's range
  // (ADVICE r1 finding).
  uint64_t off = hdr->bump.load(std::memory_order_relaxed);
  do {
    if (off + need > hdr->size) return 0;  // arena full
  } while (!hdr->bump.compare_exchange_weak(off, off + need,
                                            std::memory_order_acq_rel,
                                            std::memory_order_relaxed));
  auto* blk = reinterpret_cast<BlockHeader*>(static_cast<char*>(base) + off);
  blk->len = len;
  blk->refs.store(1);
  return off + sizeof(BlockHeader);
}

uint8_t* shm_ptr(void* base, uint64_t payload_off) {
  return reinterpret_cast<uint8_t*>(base) + payload_off;
}

static BlockHeader* blk_of(void* base, uint64_t payload_off) {
  return reinterpret_cast<BlockHeader*>(static_cast<char*>(base) +
                                        payload_off - sizeof(BlockHeader));
}

void shm_incref(void* base, uint64_t payload_off) {
  blk_of(base, payload_off)->refs.fetch_add(1);
}

// Returns refcount after decrement (block memory reclaimed only on reset).
int64_t shm_decref(void* base, uint64_t payload_off) {
  return blk_of(base, payload_off)->refs.fetch_sub(1) - 1;
}

// Reset the bump pointer (all blocks must be released; epoch-style reuse,
// which is the DataLoader pattern: arena per epoch/prefetch window).
void shm_arena_reset(void* base) {
  auto* hdr = static_cast<ArenaHeader*>(base);
  hdr->bump.store(sizeof(ArenaHeader));
}

uint64_t shm_arena_used(void* base) {
  return static_cast<ArenaHeader*>(base)->bump.load();
}

// ---------------------------------------------------------------------------
// StatRegistry: named int64 gauges (monitor.h STAT_ADD analog).
// ---------------------------------------------------------------------------

static std::mutex g_stats_mu;
static std::map<std::string, int64_t>& stats() {
  static std::map<std::string, int64_t> s;
  return s;
}

void stat_add(const char* name, int64_t v) {
  std::lock_guard<std::mutex> g(g_stats_mu);
  stats()[name] += v;
}

void stat_set(const char* name, int64_t v) {
  std::lock_guard<std::mutex> g(g_stats_mu);
  stats()[name] = v;
}

int64_t stat_get(const char* name) {
  std::lock_guard<std::mutex> g(g_stats_mu);
  auto it = stats().find(name);
  return it == stats().end() ? 0 : it->second;
}

// Fill up to cap entries; returns count. Names joined by '\n' into out_names.
int64_t stat_dump(char* out_names, int64_t cap_bytes, int64_t* out_vals,
                  int64_t cap_vals) {
  std::lock_guard<std::mutex> g(g_stats_mu);
  std::string joined;
  int64_t n = 0;
  for (auto& kv : stats()) {
    if (n >= cap_vals) break;
    if ((int64_t)(joined.size() + kv.first.size() + 1) > cap_bytes) break;
    joined += kv.first;
    joined += '\n';
    out_vals[n++] = kv.second;
  }
  std::memcpy(out_names, joined.data(), joined.size());
  if ((int64_t)joined.size() < cap_bytes) out_names[joined.size()] = 0;
  return n;
}

}  // extern "C"
