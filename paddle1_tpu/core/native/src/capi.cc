// C inference API over the paddle1_tpu Predictor.
//
// Analog of the reference's C inference API
// (/root/reference/paddle/fluid/inference/capi/ — PD_NewAnalysisConfig,
// PD_NewPredictor, PD_PredictorRun, c_api.cc), which wraps the C++
// AnalysisPredictor for non-C++ consumers (the Go bindings sit on it).
//
// TPU-native inversion: the executable program is serialized StableHLO run
// by the XLA runtime, whose supported embedding is the Python `jax` API —
// so this C ABI hosts an embedded CPython interpreter (the image's
// sanctioned binding route; no pybind11) and drives
// paddle1_tpu.inference.Predictor through the CPython C API. A C (or Go,
// via cgo) deployment links this .so plus libpython and never writes a
// line of Python.
//
// Surface (mirrors PD_* naming):
//   p1_predictor_create(model_base, device)  -> handle | NULL
//   p1_predictor_num_inputs(h) / p1_predictor_num_outputs(h)
//   p1_predictor_run_f32(h, inputs..., out_idx, out_buf, ...)
//   p1_predictor_destroy(h)
//   p1_last_error() -> static string
//
// Build: g++ -O2 -shared -fPIC -std=c++17 capi.cc -o libpaddle1_capi.so
//        $(python3-config --includes --ldflags --embed)

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_mu;
std::string g_last_error;
bool g_py_inited = false;

void set_error(const char* where) {
  std::string msg = where;
  if (PyErr_Occurred()) {
    PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    if (value) {
      PyObject* s = PyObject_Str(value);
      if (s) {
        msg += ": ";
        msg += PyUnicode_AsUTF8(s);
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  }
  g_last_error = msg;
}

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL so every entry point can take it via PyGILState.
    PyEval_SaveThread();
    g_py_inited = true;
  }
}

struct P1Predictor {
  PyObject* predictor;  // paddle1_tpu.inference.Predictor
  int n_inputs;
  int n_outputs;
  std::vector<std::string> input_names;   // cached at create
  std::vector<std::string> output_names;
  PyObject* last_outputs = nullptr;  // run_only → fetch cache
};

// Build the numpy input list from the flat C buffers; returns a new
// reference (or nullptr with g_last_error set).
PyObject* build_inputs(PyObject* np, const float** inputs,
                       const int64_t* shapes, const int* ndims,
                       int n_inputs) {
  PyObject* arglist = PyList_New(n_inputs);
  if (!arglist) { set_error("alloc arg list"); return nullptr; }
  const int64_t* sp = shapes;
  for (int i = 0; i < n_inputs; ++i) {
    int64_t numel = 1;
    PyObject* shape = PyTuple_New(ndims[i]);
    for (int d = 0; d < ndims[i]; ++d) {
      numel *= sp[d];
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(sp[d]));
    }
    sp += ndims[i];
    PyObject* mv = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<float*>(inputs[i])),
        numel * sizeof(float), PyBUF_READ);
    PyObject* flat =
        mv ? PyObject_CallMethod(np, "frombuffer", "Os", mv, "float32")
           : nullptr;
    PyObject* arr =
        flat ? PyObject_CallMethod(flat, "reshape", "O", shape)
             : nullptr;
    Py_XDECREF(mv);
    Py_XDECREF(flat);
    Py_DECREF(shape);
    if (!arr) {
      set_error("build input array");
      Py_DECREF(arglist);
      return nullptr;
    }
    PyList_SET_ITEM(arglist, i, arr);  // steals
  }
  return arglist;
}

// Copy output out_idx of `outs` into the caller's buffer. Returns 0
// on success.
int copy_output(PyObject* np, PyObject* outs, int out_idx,
                float* out_buf, int64_t out_capacity,
                int64_t* out_shape, int* out_ndim) {
  PyObject* out = PyList_GetItem(outs, out_idx);  // borrowed
  if (!out) { set_error("output index out of range"); return 1; }
  PyObject* out32 = PyObject_CallMethod(np, "ascontiguousarray", "Os",
                                        out, "float32");
  if (!out32) { set_error("ascontiguousarray"); return 1; }
  PyObject* shape = PyObject_GetAttrString(out32, "shape");
  int rank = static_cast<int>(PyTuple_Size(shape));
  int64_t numel = 1;
  for (int d = 0; d < rank; ++d) {
    int64_t v = PyLong_AsLongLong(PyTuple_GetItem(shape, d));
    if (d < *out_ndim) out_shape[d] = v;
    numel *= v;
  }
  Py_DECREF(shape);
  if (rank > *out_ndim) {
    // distinct from the data-capacity case: growing the data buffer
    // can never fix a rank overflow, and callers retry on the other
    g_last_error = "output rank exceeds shape capacity";
    Py_DECREF(out32);
    return 1;
  }
  if (numel > out_capacity) {
    g_last_error = "output buffer/shape capacity too small";
    Py_DECREF(out32);
    return 1;
  }
  *out_ndim = rank;
  PyObject* bytes = PyObject_CallMethod(out32, "tobytes", nullptr);
  Py_DECREF(out32);
  if (!bytes) { set_error("tobytes"); return 1; }
  std::memcpy(out_buf, PyBytes_AsString(bytes), numel * sizeof(float));
  Py_DECREF(bytes);
  return 0;
}

bool read_names(PyObject* pred, const char* method,
                std::vector<std::string>* out) {
  PyObject* names = PyObject_CallMethod(pred, method, nullptr);
  if (!names) { set_error(method); return false; }
  int n = static_cast<int>(PyList_Size(names));
  for (int i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(names, i);  // borrowed
    const char* s = item ? PyUnicode_AsUTF8(item) : nullptr;
    out->push_back(s ? s : "");
  }
  Py_DECREF(names);
  return true;
}

}  // namespace

extern "C" {

const char* p1_last_error() { return g_last_error.c_str(); }

// device: "auto" | "cpu" | "tpu"
void* p1_predictor_create(const char* model_base, const char* device) {
  std::lock_guard<std::mutex> lk(g_mu);
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = nullptr;
  PyObject* cfg = nullptr;
  PyObject* pred = nullptr;
  do {
    mod = PyImport_ImportModule("paddle1_tpu.inference");
    if (!mod) { set_error("import paddle1_tpu.inference"); break; }
    cfg = PyObject_CallMethod(mod, "Config", "ss", model_base,
                              (std::string(model_base) + ".pdiparams")
                                  .c_str());
    if (!cfg) { set_error("Config()"); break; }
    if (device && std::strcmp(device, "cpu") == 0) {
      PyObject* r = PyObject_CallMethod(cfg, "disable_gpu", nullptr);
      if (!r) { set_error("disable_gpu()"); break; }
      Py_DECREF(r);
    } else if (device && std::strcmp(device, "tpu") == 0) {
      PyObject* r = PyObject_CallMethod(cfg, "enable_tpu", nullptr);
      if (!r) { set_error("enable_tpu()"); break; }
      Py_DECREF(r);
    }
    pred = PyObject_CallMethod(mod, "create_predictor", "O", cfg);
    if (!pred) { set_error("create_predictor()"); break; }

    std::vector<std::string> in_names, out_names;
    if (!read_names(pred, "get_input_names", &in_names)) break;
    if (!read_names(pred, "get_output_names", &out_names)) break;
    int n_in = static_cast<int>(in_names.size());
    int n_out = static_cast<int>(out_names.size());

    auto* h = new P1Predictor{pred, n_in, n_out,
                              std::move(in_names),
                              std::move(out_names)};
    pred = nullptr;  // ownership moved
    result = h;
  } while (false);
  Py_XDECREF(pred);
  Py_XDECREF(cfg);
  Py_XDECREF(mod);
  PyGILState_Release(gil);
  return result;
}

int p1_predictor_num_inputs(void* handle) {
  return handle ? static_cast<P1Predictor*>(handle)->n_inputs : -1;
}

int p1_predictor_num_outputs(void* handle) {
  return handle ? static_cast<P1Predictor*>(handle)->n_outputs : -1;
}

// Name accessors (reference PD_GetInputName/PD_GetOutputName): the
// returned pointer stays valid for the life of the predictor handle.
const char* p1_predictor_input_name(void* handle, int i) {
  if (!handle) return nullptr;
  auto* h = static_cast<P1Predictor*>(handle);
  if (i < 0 || i >= static_cast<int>(h->input_names.size()))
    return nullptr;
  return h->input_names[i].c_str();
}

const char* p1_predictor_output_name(void* handle, int i) {
  if (!handle) return nullptr;
  auto* h = static_cast<P1Predictor*>(handle);
  if (i < 0 || i >= static_cast<int>(h->output_names.size()))
    return nullptr;
  return h->output_names[i].c_str();
}

// Run with n_inputs f32 tensors; copy output out_idx into out_buf.
// shapes: flattened per-input dims; ndims: per-input rank.
// Returns 0 on success; fills out_shape (up to *out_ndim entries, which
// on entry holds the capacity of out_shape) and the real rank.
int p1_predictor_run_f32(void* handle, const float** inputs,
                         const int64_t* shapes, const int* ndims,
                         int n_inputs, int out_idx, float* out_buf,
                         int64_t out_capacity, int64_t* out_shape,
                         int* out_ndim) {
  if (!handle) {
    g_last_error = "null predictor handle";
    return 1;
  }
  auto* h = static_cast<P1Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject* np = nullptr;
  PyObject* arglist = nullptr;
  PyObject* outs = nullptr;
  do {
    np = PyImport_ImportModule("numpy");
    if (!np) { set_error("import numpy"); break; }
    arglist = build_inputs(np, inputs, shapes, ndims, n_inputs);
    if (!arglist) break;
    outs = PyObject_CallMethod(h->predictor, "run", "O", arglist);
    if (!outs) { set_error("Predictor.run"); break; }
    rc = copy_output(np, outs, out_idx, out_buf, out_capacity,
                     out_shape, out_ndim);
  } while (false);
  Py_XDECREF(outs);
  Py_XDECREF(arglist);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return rc;
}

// Run ONCE and cache all outputs on the handle; read them out with
// p1_predictor_fetch_f32. This is the multi-output path (the Go
// ZeroCopyRun): one forward execution regardless of output count.
int p1_predictor_run_only_f32(void* handle, const float** inputs,
                              const int64_t* shapes, const int* ndims,
                              int n_inputs) {
  if (!handle) {
    g_last_error = "null predictor handle";
    return 1;
  }
  auto* h = static_cast<P1Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject* np = nullptr;
  PyObject* arglist = nullptr;
  // drop the previous run's cache up front: a failed run must not
  // leave stale outputs a later fetch would return as fresh
  Py_XDECREF(h->last_outputs);
  h->last_outputs = nullptr;
  do {
    np = PyImport_ImportModule("numpy");
    if (!np) { set_error("import numpy"); break; }
    arglist = build_inputs(np, inputs, shapes, ndims, n_inputs);
    if (!arglist) break;
    PyObject* outs = PyObject_CallMethod(h->predictor, "run", "O",
                                         arglist);
    if (!outs) { set_error("Predictor.run"); break; }
    h->last_outputs = outs;  // ownership moved to the handle
    rc = 0;
  } while (false);
  Py_XDECREF(arglist);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return rc;
}

// Copy output out_idx of the last p1_predictor_run_only_f32 call.
int p1_predictor_fetch_f32(void* handle, int out_idx, float* out_buf,
                           int64_t out_capacity, int64_t* out_shape,
                           int* out_ndim) {
  if (!handle) {
    g_last_error = "null predictor handle";
    return 1;
  }
  auto* h = static_cast<P1Predictor*>(handle);
  if (!h->last_outputs) {
    g_last_error = "fetch before p1_predictor_run_only_f32";
    return 1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject* np = PyImport_ImportModule("numpy");
  if (np) {
    rc = copy_output(np, h->last_outputs, out_idx, out_buf,
                     out_capacity, out_shape, out_ndim);
    Py_DECREF(np);
  } else {
    set_error("import numpy");
  }
  PyGILState_Release(gil);
  return rc;
}

void p1_predictor_destroy(void* handle) {
  if (!handle) return;
  auto* h = static_cast<P1Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->last_outputs);
  Py_XDECREF(h->predictor);
  PyGILState_Release(gil);
  delete h;
}

}  // extern "C"
