"""Runtime lock-order sanitizer (ISSUE 11).

The static pass (``tools/lint/lock_discipline.py``) sees lexical
nesting; it cannot see an acquisition order composed across method
calls — fleet lock, then a metrics lock inside ``counter()``, then a
supervisor table lock three frames down. This module covers that
dynamically, the TSan-lite way:

* Hot classes construct their locks through :func:`make_lock` /
  :func:`make_rlock`. With the ``debug_lock_sanitizer`` flag OFF (the
  default) these return **plain** ``threading.Lock``/``RLock`` — the
  disabled cost is structurally zero (one flag read at construction,
  nothing on acquire/release; the test asserts the returned type IS
  the stdlib type).

* With the flag ON (the CI concurrency lanes), every acquisition
  records the edge ``held -> acquiring`` in one process-wide order
  graph, keyed by lock *name*. Acquiring B while holding A when some
  thread previously acquired A while holding B raises the typed
  :class:`LockOrderError` at the second site — the deadlock that
  would otherwise need the exact unlucky interleaving to manifest
  fires deterministically on ANY run that exercises both orders.
  Reentrant RLock re-acquisition records nothing.

* :func:`note_blocking` marks a blocking region (a socket ``recv``, a
  future wait). Under the sanitizer, entering one while the current
  thread holds ANY sanitized lock raises the typed
  :class:`BlockingUnderLockError` — the hold-while-blocking class
  (PR 7's ``sendall``-under-lock) caught at runtime wherever the
  static pass's lexical view ran out. Zero-cost when off: one module
  bool test, no allocation.

Edges are keyed by name, not object identity: two fleets' ``_lock``
instances are the same DISCIPLINE, and keying by name makes the order
graph survive object churn (and stay readable in the error message).
Names default to ``<ClassName attr>``-style strings passed by the
construction sites.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .errors import EnforceNotMet

__all__ = ["LockOrderError", "BlockingUnderLockError", "make_lock",
           "make_rlock", "note_blocking", "sanitizing", "held_locks",
           "reset_order_graph"]


class LockOrderError(EnforceNotMet):
    """Two locks were acquired in opposite orders by (possibly)
    different threads — a latent deadlock."""


class BlockingUnderLockError(EnforceNotMet):
    """A blocking call ran while the thread held a sanitized lock."""


# flipped True the first time a sanitized lock is constructed — the
# only cost note_blocking() pays when the sanitizer never armed
_armed = False

_graph_lock = threading.Lock()
# (before, after) -> "thread/site" note of the first time that order
# was observed; the evidence quoted when the inverse order shows up
_order: Dict[Tuple[str, str], str] = {}

_tls = threading.local()


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """BFS over the recorded order edges (caller holds _graph_lock).
    Returns the node path src..dst, or None."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    frontier = [src]
    while frontier:
        nxt = []
        for n in frontier:
            for (a, b) in _order:
                if a != n or b in prev or b == src:
                    continue
                prev[b] = n
                if b == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                nxt.append(b)
        frontier = nxt
    return None


def _held() -> List["_SanitizedLock"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def sanitizing() -> bool:
    """Whether the ``debug_lock_sanitizer`` flag is on (read per call —
    construction-time decisions go through make_lock)."""
    from . import flags as core_flags
    return bool(core_flags.flag("debug_lock_sanitizer"))


def reset_order_graph() -> None:
    """Drop recorded acquisition orders (test isolation)."""
    with _graph_lock:
        _order.clear()


def held_locks() -> List[str]:
    """Names of sanitized locks the current thread holds (tests)."""
    return [lk.name for lk in _held()]


class _SanitizedLock:
    """Order-recording wrapper with the ``threading.Lock`` surface
    (plus what ``threading.Condition`` needs: ``acquire``/``release``
    and context management; Condition's ``_is_owned`` fallback probes
    ``acquire(False)``, which this supports)."""

    _reentrant = False

    def __init__(self, name: str, allow_blocking: bool = False):
        self.name = name
        self.allow_blocking = allow_blocking
        self._lock = (threading.RLock() if self._reentrant
                      else threading.Lock())

    # -- order bookkeeping --------------------------------------------------

    def _before_acquire(self) -> None:
        held = _held()
        if not held:
            return
        if self._reentrant and any(lk is self for lk in held):
            return  # reentrant re-acquisition: no new edge
        me = self.name
        tname = threading.current_thread().name
        for prior in held:
            if prior is self:
                continue
            a, b = prior.name, me
            if a == b:
                # two DIFFERENT instances sharing a name, nested: the
                # name-keyed graph cannot order them — and if the same
                # pair ever nests the other way round the deadlock is
                # invisible to it. Typed, with the fix in the message.
                raise LockOrderError(
                    f"nested acquisition of two distinct locks both "
                    f"named '{a}' (thread '{tname}') — the sanitizer "
                    "orders locks BY NAME, so same-name nesting is "
                    "unverifiable; give the instances distinct names "
                    "(e.g. make_lock(f'Class[{rank}].lock')) or don't "
                    "nest them")
            with _graph_lock:
                # an inversion is any recorded PATH b ->* a (direct or
                # transitive: A->B, B->C elsewhere makes C-while-
                # holding-A a 3-lock cycle) — lockdep-style closure;
                # a != b here, so a found path always has >= 2 nodes
                path = _find_path(b, a)
                if path is not None:
                    raise LockOrderError(
                        f"lock-order inversion: thread '{tname}' is "
                        f"acquiring '{b}' while holding '{a}', but "
                        "the opposite order "
                        + " -> ".join(path)
                        + f" was previously observed "
                        f"({_order.get((path[0], path[1]), '?')}) — "
                        "threads running these paths concurrently "
                        "deadlock; pick one global order")
                _order.setdefault((a, b), f"thread '{tname}'")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._before_acquire()
        got = (self._lock.acquire(blocking, timeout) if blocking
               else self._lock.acquire(False))
        if got:
            _held().append(self)
        return got

    def release(self) -> None:
        self._lock.release()
        held = _held()
        # remove the most recent entry for THIS lock (locks are almost
        # always released LIFO, but nothing requires it)
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<SanitizedLock {self.name!r}>"


class _SanitizedRLock(_SanitizedLock):
    _reentrant = True


def make_lock(name: str,
              allow_blocking: bool = False) -> "threading.Lock":
    """A mutex for a hot shared structure: plain ``threading.Lock``
    unless ``debug_lock_sanitizer`` is on, then an order-recording
    wrapper. ``name`` keys the process-wide order graph — use a
    stable ``Class.attr``-style string. ``allow_blocking=True``
    declares an *administrative* mutex DESIGNED to be held across
    blocking operations (a deploy roll, a one-shot build): it still
    participates in order tracking, but holding it does not trip
    :func:`note_blocking` — the declaration is greppable and
    deliberate, like a ``# noqa`` with a type signature."""
    global _armed
    if not sanitizing():
        return threading.Lock()
    _armed = True
    return _SanitizedLock(name, allow_blocking)


def make_rlock(name: str,
               allow_blocking: bool = False) -> "threading.RLock":
    global _armed
    if not sanitizing():
        return threading.RLock()
    _armed = True
    return _SanitizedRLock(name, allow_blocking)


def note_blocking(what: str) -> None:
    """Mark a blocking region (socket recv, future wait). Under the
    sanitizer, raises typed when the current thread holds any
    sanitized lock — the hold-while-blocking class. Free when the
    sanitizer never armed (one module bool test)."""
    if not _armed:
        return
    held = [lk for lk in _held() if not lk.allow_blocking]
    if held:
        tname = threading.current_thread().name
        raise BlockingUnderLockError(
            f"blocking call ({what}) on thread '{tname}' while "
            f"holding sanitized lock(s) "
            f"{[lk.name for lk in held]} — every thread needing them "
            "convoys behind this wait; release before blocking")
