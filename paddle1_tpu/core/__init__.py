"""Core runtime: dtype, place, flags, errors, RNG, Tensor."""

from . import errors, flags
# NOTE: do NOT bind the name `dtype` here — it would shadow the core.dtype
# submodule for every `from ..core import dtype as dtypes` import site.
from .dtype import (bfloat16, bool_, complex128, complex64, convert_dtype,
                    float16, float32, float64, get_default_dtype,
                    int16, int32, int64, int8, promote_types,
                    set_default_dtype, uint8)
from .errors import *  # noqa: F401,F403
from .flags import flags_guard, get_flags, set_flags
from .generator import (Generator, default_generator, get_rng_state,
                        get_rng_tracker, next_key, rng_scope, seed,
                        set_rng_state)
from .place import (CPUPlace, Place, TPUPlace, device_count, device_guard,
                    get_device, is_compiled_with_tpu, set_device)
from .indexed_slices import IndexedSlices
from .tensor import Parameter, Tensor, to_tensor
