"""Deterministic chaos injection for the fault-tolerance stack.

Long multi-host runs die to a short list of causes — a NaN batch out of a
corrupt shard, a checkpoint write killed mid-flight, a dataloader worker
raising, a preemption notice — and the recovery code for each is exactly
the code that never runs in a clean test environment. This module makes
those failures *first-class, reproducible inputs*: each injection point
carries a monotone occurrence counter, and a chaos spec arms specific
occurrences ("the 3rd batch", "the 2nd checkpoint write"). Because the
counters are deterministic, a failure fires exactly once per armed
occurrence — so a retried/replayed operation comes back clean, which is
what lets the resilience tests assert bit-parity between an interrupted
run and an uninterrupted one.

Spec syntax (comma-separated, each entry ``point@N`` with 1-based N;
repeat a point to arm several occurrences)::

    nan_batch@3,ckpt_fail@2,preempt@7,loader_raise@5

Worker-level points take an optional rank qualifier ``point@N:R`` —
"fire on the Nth health beat of rank R" (without ``:R`` every rank
fires on its Nth beat; chaos state is process-local, so each worker
counts its own beats)::

    worker_kill@5:1,worker_hang@8:0

Dataloader-level points use the same qualifier with a *worker id*
(``loader_worker_kill@3:0`` = worker 0's 3rd task; the single-process
loader path counts as worker 0). They are armed in the loader worker
PROCESS (the parent forwards :func:`active_spec` at spawn) and only in
loader-worker incarnation 0, so a recovered/re-spawned worker replays
clean — the same fire-once contract as the PR 3 supervisor points::

    loader_worker_kill@4:0,corrupt_sample@3:1,loader_stall@2:0

Armed via :func:`configure` or the ``FLAGS_ft_chaos`` env/flag (read by
``configure_from_flags``). All state is process-local and reset by
:func:`reset`.

Injection points
----------------
``nan_batch``     — :func:`maybe_poison` rewrites the first floating leaf
                    of the batch to NaN (a corrupt input shard).
``ckpt_fail``     — :func:`check_checkpoint_write` raises ``IOError``
                    inside ``CheckpointManager.save`` *before* the commit
                    rename, leaving a partial tmp dir behind (a write
                    killed mid-flight).
``loader_raise``  — :func:`check_loader` raises inside the DataLoader
                    prefetch producer (a worker crash).
``preempt``       — :func:`check_preempt` raises
                    :class:`SimulatedPreemption` (the maintenance-event
                    signal; also raised after :func:`request_preemption`,
                    which is safe to call from a real signal handler).
``serve_slow_step`` — :func:`check_serve_slow` returns True on an armed
                    serving micro-batch dispatch; the Batcher stalls
                    that dispatch for ``serve_chaos_slow_s`` seconds (a
                    slow/hiccuping executable — the deadline-expiry and
                    shed paths' reproducible trigger).

Worker-level points (checked by :func:`check_worker` from
``core.health.beat``, i.e. once per training step of a *supervised*
worker; incarnation 0 only, so a restarted worker replays clean):

``worker_kill``      — SIGKILL self (an ungraceful worker death the
                       Supervisor must detect via ``poll`` and restart
                       from the last committed checkpoint — or, under
                       the elastic ``resize`` policy, answer with a
                       shrink-and-continue into a smaller world; the
                       incarnation-0 gate below is what keeps the kill
                       from re-firing in every resized life).
``worker_hang``      — stop beating and block forever (a deadlocked
                       queue / stuck collective; the Supervisor's
                       heartbeat ager must catch it, collect a SIGABRT
                       stack dump, and respond per policy).
``worker_unhealthy`` — write the explicit unhealthy marker and keep
                       running (a worker that knows it is broken).

Dataloader-level points (checked inside the input pipeline; all pure
bookkeeping — the loader performs the kill/sleep/raise):

``loader_worker_kill`` — :func:`check_loader_worker_kill` on the Nth
                       task a loader worker picks up; the worker
                       SIGKILLs itself (an OOM-killed decode process
                       the parent must detect and re-spawn).
``corrupt_sample``   — :func:`check_sample` raises
                       :class:`ChaosInjectedError` on the Nth sample
                       fetch (a corrupt record; drives the
                       ``loader_bad_sample`` skip/quarantine policy).
``loader_stall``     — :func:`check_loader_stall` True on the Nth
                       task/batch; the loader sleeps
                       ``loader_chaos_stall_s`` (a wedged reader the
                       input-stall watchdog must catch).

Serving-replica points (checked by :func:`check_replica` from the
replica worker's request loop — one shared counter per replica process,
qualifier = the replica's fleet rank; armed in the replica PROCESS via
the spec the fleet forwards at spawn, and only in incarnation 0 so a
supervisor-restarted replica replays clean):

``replica_kill``     — SIGKILL self mid-request (an OOM-killed serving
                       worker; the fleet must fail over its in-flight
                       requests to a healthy replica and the Supervisor
                       must relaunch it).
``replica_hang``     — stop reading the fleet connection and block
                       forever (a wedged RPC plane; the fleet's
                       per-request transport timeout + circuit breaker
                       is the detector — the replica's Batcher keeps
                       heartbeating, so Popen/heartbeat watching alone
                       would never notice).
``replica_slow``     — handle this request only after sleeping
                       ``serve_chaos_slow_s`` (a hiccuping replica —
                       drives the adaptive-admission overload path).

Collective-schedule point (checked by :func:`check_collective` from
the ``distributed/collective.py`` wrappers — one shared counter per
process, qualifier = the trainer rank)::

``collective_skip``  — ``collective_skip@N[:R]``: rank R (any rank
                       when unqualified) SKIPS its Nth collective op —
                       the wrapper returns its input untouched and
                       journals nothing, seeding exactly the
                       rank-divergent schedule the collective-schedule
                       sanitizer's cross-rank verifier must turn into
                       a typed CollectiveDivergenceError (on hardware
                       this shape deadlocks).

Generative-serving points (checked by :func:`check_gen_step` once per
continuous-batching decode step; the qualifier is a SLOT id)::

``gen_slot_wedge``   — ``gen_slot_wedge@N[:S]``: on the Nth decode
                       step, slot S (the lowest active slot when
                       unqualified) is declared wedged. The engine must
                       fail ONLY that slot's TokenStream typed, release
                       the slot, and leave cohabiting sequences
                       bit-identical to an uncontended run — the
                       continuous-batching isolation contract.
``gen_slow_step``    — stall the Nth decode dispatch for
                       ``serve_chaos_slow_s`` (drives the mid-stream
                       wall-deadline path). Action belongs to the
                       engine loop; this stays pure bookkeeping.

Generation-fleet points (the GenerationFleet's mid-stream failure
matrix; armed in the gen-replica PROCESS via the spec the fleet
forwards at spawn, incarnation 0 only so a restarted replica replays
clean):

``gen_replica_kill`` — checked by :func:`check_gen_replica` once per
                       TOKEN FRAME the gen replica streams back
                       (qualifier = fleet rank): SIGKILL self MID-
                       STREAM, after some tokens have already reached
                       the client — the fleet must re-admit every
                       in-flight stream on a survivor from
                       ``prompt + tokens already emitted`` and the
                       continuation must be bit-identical.
``gen_replica_hang`` — same counter: stop streaming frames (and stay
                       otherwise alive and heartbeating) — the
                       wedged-stream class only the fleet's stream-
                       silence deadline can catch.
``gen_page_pressure`` — checked by :func:`check_gen_pressure` once per
                       scheduler tick (own counter, no qualifier): the
                       scheduler claims every free KV page and holds
                       them for a few ticks — forcing decode page
                       faults so the preemption path (shed prefix
                       cache, preempt lowest-priority stream, park +
                       re-admit bit-identically) runs deterministically.

Parameter-server points (checked by :func:`check_ps` once per REQUEST
the TableServer handles; armed in the PS server PROCESS via the env
the owner forwards at spawn, qualifier = the PS server rank):

``ps_kill``          — ``ps_kill@N[:R]``: SIGKILL self on the Nth
                       request, AFTER applying + checkpointing it but
                       BEFORE acking — the client's bounded
                       retry/reconnect replays the un-acked request
                       into the restarted-from-checkpoint server, and
                       the push-epoch fence must make the replay
                       idempotent (exactly-once even when the dead
                       server DID apply it).
``ps_hang``          — stall the Nth request past the client's socket
                       timeout (a wedged PS — the retry path's
                       reconnect must turn it into a stall, not a
                       trainer crash).

Delta-pipeline points (checked inside ``DeltaLog.publish``; each
counts its own publishes, qualifier unused):

``delta_corrupt``    — bit-flip the Nth published delta file after its
                       CRC was computed: the subscriber's verify must
                       skip + count it, never apply it.
``delta_gap``        — after the Nth publish, prune every older delta
                       from under any lagging reader: the subscriber
                       must surface a typed ``DeltaGapDetected`` and
                       resync from a snapshot instead of silently
                       serving stale rows.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple, Union

__all__ = [
    "SimulatedPreemption", "ChaosInjectedError", "configure",
    "configure_from_flags", "reset", "enabled", "fire", "counts",
    "active_spec",
    "maybe_poison", "check_checkpoint_write", "check_loader",
    "check_preempt", "check_serve_slow", "check_worker",
    "check_sample", "check_loader_worker_kill", "check_loader_stall",
    "check_replica", "check_gen_step", "check_collective",
    "check_gen_replica", "check_gen_pressure",
    "check_ps", "check_delta_corrupt", "check_delta_gap",
    "request_preemption", "preemption_requested",
    "POISON_BATCH", "CKPT_FAIL", "LOADER_RAISE", "PREEMPT", "SERVE_SLOW",
    "WORKER_KILL", "WORKER_HANG", "WORKER_UNHEALTHY",
    "LOADER_WORKER_KILL", "CORRUPT_SAMPLE", "LOADER_STALL",
    "REPLICA_KILL", "REPLICA_HANG", "REPLICA_SLOW",
    "GEN_SLOT_WEDGE", "GEN_SLOW_STEP", "COLLECTIVE_SKIP",
    "GEN_REPLICA_KILL", "GEN_REPLICA_HANG", "GEN_PAGE_PRESSURE",
    "PS_KILL", "PS_HANG", "DELTA_CORRUPT", "DELTA_GAP",
]

POISON_BATCH = "nan_batch"
CKPT_FAIL = "ckpt_fail"
LOADER_RAISE = "loader_raise"
PREEMPT = "preempt"
SERVE_SLOW = "serve_slow_step"
WORKER_KILL = "worker_kill"
WORKER_HANG = "worker_hang"
WORKER_UNHEALTHY = "worker_unhealthy"
LOADER_WORKER_KILL = "loader_worker_kill"
CORRUPT_SAMPLE = "corrupt_sample"
LOADER_STALL = "loader_stall"
REPLICA_KILL = "replica_kill"
REPLICA_HANG = "replica_hang"
REPLICA_SLOW = "replica_slow"
GEN_SLOT_WEDGE = "gen_slot_wedge"
GEN_SLOW_STEP = "gen_slow_step"
COLLECTIVE_SKIP = "collective_skip"
GEN_REPLICA_KILL = "gen_replica_kill"
GEN_REPLICA_HANG = "gen_replica_hang"
GEN_PAGE_PRESSURE = "gen_page_pressure"
PS_KILL = "ps_kill"
PS_HANG = "ps_hang"
DELTA_CORRUPT = "delta_corrupt"
DELTA_GAP = "delta_gap"

_WORKER_POINTS = (WORKER_KILL, WORKER_HANG, WORKER_UNHEALTHY)
# loader points share the worker points' ":qualifier" grammar, but the
# qualifier is a LOADER worker id, not a trainer rank
_LOADER_POINTS = (LOADER_WORKER_KILL, CORRUPT_SAMPLE, LOADER_STALL)
# serving-replica points: the qualifier is the REPLICA rank in its fleet
_REPLICA_POINTS = (REPLICA_KILL, REPLICA_HANG, REPLICA_SLOW)
# generative-serving points: the qualifier is a decode SLOT id; both
# share the per-step counter check_gen_step advances
_GEN_POINTS = (GEN_SLOT_WEDGE, GEN_SLOW_STEP)
# collective-schedule point: the qualifier is the trainer rank
_COLLECTIVE_POINTS = (COLLECTIVE_SKIP,)
# generation-fleet points: kill/hang share one token-frame counter
# (qualifier = gen-replica fleet rank); page pressure counts its own
# scheduler ticks (qualifier unused)
_GEN_FLEET_POINTS = (GEN_REPLICA_KILL, GEN_REPLICA_HANG,
                     GEN_PAGE_PRESSURE)
# parameter-server points: kill/hang share one REQUEST counter
# (qualifier = the PS server rank)
_PS_POINTS = (PS_KILL, PS_HANG)
# delta-pipeline points: each counts its own publishes (qualifier unused)
_DELTA_POINTS = (DELTA_CORRUPT, DELTA_GAP)
_QUALIFIED_POINTS = (_WORKER_POINTS + _LOADER_POINTS + _REPLICA_POINTS
                     + _GEN_POINTS + _COLLECTIVE_POINTS
                     + _GEN_FLEET_POINTS + _PS_POINTS + _DELTA_POINTS)
_POINTS = (POISON_BATCH, CKPT_FAIL, LOADER_RAISE,
           PREEMPT, SERVE_SLOW) + _QUALIFIED_POINTS


class SimulatedPreemption(BaseException):
    """A (simulated) preemption notice.

    Deliberately a ``BaseException`` — like ``KeyboardInterrupt`` — so
    that transient-failure retry wrappers written as ``except Exception``
    can never swallow it: a preemption must unwind to the resilient
    loop's preemption handler, not be retried in place.

    ``graceful=True`` marks a real advance NOTICE (the SIGTERM grace
    window of :func:`request_preemption`): the handler still has time
    to checkpoint the current known-good state, losing nothing. The
    armed ``preempt@N`` chaos point simulates the opposite — an
    ungraceful kill with no chance to save — and restores+replays.
    """

    def __init__(self, *args, graceful: bool = False):
        super().__init__(*args)
        self.graceful = graceful


class ChaosInjectedError(IOError):
    """The error raised by armed ``ckpt_fail``/``loader_raise`` points
    (an IOError: both model real I/O failures)."""


_lock = threading.Lock()
# point -> set of armed 1-based occurrence indices
_armed: Dict[str, set] = {}
# worker/loader point -> set of (occurrence, qualifier-or-None) pairs
_armed_worker: Dict[str, set] = {}
# point -> occurrences seen so far
_counters: Dict[str, int] = {}
_preempt_requested = False
# the spec string this process was armed with (canonical form) — what a
# parent forwards to spawned dataloader workers so they can arm their
# own process-local counters
_spec_str: str = ""


def reset() -> None:
    """Disarm every point and zero all counters (test isolation)."""
    global _preempt_requested, _spec_str
    with _lock:
        _armed.clear()
        _armed_worker.clear()
        _counters.clear()
        _preempt_requested = False
        _spec_str = ""


def configure(spec: Union[str, Dict[str, object], None]) -> None:
    """Arm injection points from a spec string (``"nan_batch@3,..."``;
    worker points take ``worker_kill@N:R`` = Nth beat of rank R) or a
    dict ``{point: N-or-list-of-N}``. Resets previous arming/counters."""
    reset()
    if not spec:
        return
    entries: List[Tuple[str, int, Optional[int]]] = []
    if isinstance(spec, str):
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            if "@" not in raw:
                raise ValueError(
                    f"chaos spec entry {raw!r} must be 'point@N' "
                    f"(points: {', '.join(_POINTS)})")
            name, _, n = raw.partition("@")
            n, colon, rank = n.partition(":")
            try:
                entries.append((name.strip(), int(n),
                                int(rank) if colon else None))
            except ValueError:
                raise ValueError(
                    f"chaos spec entry {raw!r} must be 'point@N' (or "
                    f"'point@N:rank' for worker points) with integer "
                    f"N/rank") from None
    else:
        for name, ns in spec.items():
            for n in (ns if isinstance(ns, (list, tuple)) else [ns]):
                entries.append((name, int(n), None))
    global _spec_str
    with _lock:
        for name, n, rank in entries:
            if name not in _POINTS:
                raise ValueError(
                    f"unknown chaos point {name!r} "
                    f"(points: {', '.join(_POINTS)})")
            if n < 1:
                raise ValueError(f"chaos occurrence must be >= 1, got {n}")
            if rank is not None and name not in _QUALIFIED_POINTS:
                raise ValueError(
                    f"rank qualifier '@{n}:{rank}' is only valid for "
                    f"worker/loader points ({', '.join(_QUALIFIED_POINTS)})")
            if rank is not None and rank < 0:
                raise ValueError(f"chaos rank must be >= 0, got {rank}")
            if name in _QUALIFIED_POINTS:
                _armed_worker.setdefault(name, set()).add((n, rank))
            else:
                _armed.setdefault(name, set()).add(n)
        # configure() is reset-then-arm (see docstring), so the armed
        # set and the forwarded spec string stay in lockstep
        _spec_str = ",".join(
            f"{name}@{n}" + (f":{rank}" if rank is not None else "")
            for name, n, rank in entries)


def configure_from_flags() -> bool:
    """Arm from the ``ft_chaos`` flag (set via ``FLAGS_ft_chaos`` env or
    ``set_flags``). Returns True when anything was armed."""
    from . import flags as core_flags
    spec = core_flags.flag("ft_chaos")
    if spec:
        configure(spec)
        return True
    return False


def enabled() -> bool:
    """Whether any point is armed (fast gate for hot paths)."""
    return bool(_armed) or bool(_armed_worker) or _preempt_requested


def counts() -> Dict[str, int]:
    """Occurrence counters seen so far (diagnostics/tests)."""
    with _lock:
        return dict(_counters)


def active_spec() -> str:
    """The canonical spec string this process is armed with ('' when
    nothing is armed). The DataLoader forwards it to spawned worker
    processes so loader-level points count occurrences in the process
    where the work actually happens."""
    return _spec_str


def fire(point: str) -> bool:
    """Record one occurrence of ``point``; True iff this occurrence is
    armed. Each armed occurrence fires exactly once — a replay of the
    same logical operation draws a fresh (higher) occurrence number and
    comes back clean."""
    with _lock:
        n = _counters.get(point, 0) + 1
        _counters[point] = n
        return n in _armed.get(point, ())


# -- point helpers (each a 1-2 line call at the real code site) --------------

def maybe_poison(batch):
    """``nan_batch``: on an armed occurrence, return a copy of ``batch``
    with its first floating-point leaf filled with NaN."""
    if not enabled() or not fire(POISON_BATCH):
        return batch
    import numpy as np

    state = {"done": False}

    def poison(leaf):
        if state["done"]:
            return leaf
        arr = np.asarray(getattr(leaf, "data", leaf))
        if np.issubdtype(arr.dtype, np.floating):
            state["done"] = True
            return np.full_like(arr, np.nan)
        return leaf

    import jax
    poisoned = jax.tree_util.tree_map(poison, batch)
    if not state["done"]:  # integer-only batch: poison is a no-op
        return batch
    return poisoned


def check_checkpoint_write() -> None:
    """``ckpt_fail``: raise on an armed checkpoint-write occurrence."""
    if enabled() and fire(CKPT_FAIL):
        raise ChaosInjectedError(
            "chaos: injected checkpoint write failure")


def check_loader() -> None:
    """``loader_raise``: raise on an armed dataloader-batch occurrence."""
    if enabled() and fire(LOADER_RAISE):
        raise ChaosInjectedError("chaos: injected dataloader failure")


def check_serve_slow() -> bool:
    """``serve_slow_step``: True on an armed serving-dispatch occurrence.
    The *action* (sleeping ``serve_chaos_slow_s``) belongs to the
    serving Batcher — this stays pure bookkeeping, like the worker
    points."""
    return enabled() and fire(SERVE_SLOW)


def request_preemption() -> None:
    """Flag a preemption from outside the loop (signal-handler safe: just
    sets a bool). The next :func:`check_preempt` raises."""
    global _preempt_requested
    _preempt_requested = True


def preemption_requested() -> bool:
    return _preempt_requested


def check_worker(rank: int) -> Optional[str]:
    """Worker-level points, evaluated once per health beat of rank
    ``rank``. All three share one beat counter (an entry ``point@N:R``
    reads "on the Nth beat of rank R"; without ``:R`` any rank's Nth
    beat matches). Returns the fired point name — ``WORKER_KILL`` >
    ``WORKER_HANG`` > ``WORKER_UNHEALTHY`` when several arm the same
    beat — or None. The *action* (SIGKILL self / block / write the
    unhealthy marker) is performed by ``core.health``, keeping this
    module pure bookkeeping."""
    if not _armed_worker:
        return None
    with _lock:
        n = _counters.get("worker_beat", 0) + 1
        _counters["worker_beat"] = n
        for point in (WORKER_KILL, WORKER_HANG, WORKER_UNHEALTHY):
            armed = _armed_worker.get(point, ())
            if (n, None) in armed or (n, rank) in armed:
                return point
    return None


def check_replica(rank: int) -> Optional[str]:
    """Serving-replica points, evaluated once per inference request the
    replica worker ``rank`` handles. The three points share one request
    counter (an entry ``point@N:R`` reads "on the Nth request of
    replica R"; without ``:R`` any replica's Nth request matches), and
    priority is ``REPLICA_KILL`` > ``REPLICA_HANG`` > ``REPLICA_SLOW``
    when several arm the same request. The *action* (SIGKILL self /
    stop reading / sleep ``serve_chaos_slow_s``) is performed by
    ``serving.replica`` — this stays pure bookkeeping, like the
    worker points."""
    if not _armed_worker:
        return None
    with _lock:
        n = _counters.get("replica_req", 0) + 1
        _counters["replica_req"] = n
        for point in _REPLICA_POINTS:
            armed = _armed_worker.get(point, ())
            if (n, None) in armed or (n, rank) in armed:
                return point
    return None


def check_gen_step(active_slots) -> Tuple[Optional[int], bool]:
    """Generative-serving points, evaluated ONCE per continuous-batching
    decode step. Both points share one step counter: an entry
    ``gen_slot_wedge@N:S`` reads "on the Nth decode step, wedge slot S"
    (without ``:S`` the lowest active slot is wedged);
    ``gen_slow_step@N`` stalls the Nth dispatch. Returns
    ``(wedged_slot_or_None, slow)``; the *actions* (failing the slot's
    stream typed + releasing it / sleeping ``serve_chaos_slow_s``)
    belong to ``serving.generate`` — this stays pure bookkeeping, like
    every other point."""
    if not _armed_worker:
        return None, False
    active = sorted(int(s) for s in active_slots)
    with _lock:
        n = _counters.get("gen_step", 0) + 1
        _counters["gen_step"] = n
        slow = any(n == occ for occ, _ in
                   _armed_worker.get(GEN_SLOW_STEP, ()))
        wedged = None
        for occ, slot in _armed_worker.get(GEN_SLOT_WEDGE, ()):
            if occ != n:
                continue
            if slot is None:
                wedged = active[0] if active else None
            elif slot in active:
                wedged = slot
            break
    return wedged, slow


def check_gen_replica(rank: int) -> Optional[str]:
    """Generation-fleet replica points, evaluated once per TOKEN FRAME
    the gen replica ``rank`` streams back to its fleet. Kill and hang
    share one frame counter (``gen_replica_kill@N:R`` reads "on the Nth
    token frame of replica R"; without ``:R`` any replica's Nth frame
    matches); priority ``GEN_REPLICA_KILL`` > ``GEN_REPLICA_HANG`` when
    both arm the same frame. The *action* (SIGKILL self mid-stream /
    stop streaming while staying alive) is performed by
    ``serving.genreplica`` — this stays pure bookkeeping."""
    if not _armed_worker:
        return None
    with _lock:
        n = _counters.get("gen_token_frame", 0) + 1
        _counters["gen_token_frame"] = n
        for point in (GEN_REPLICA_KILL, GEN_REPLICA_HANG):
            armed = _armed_worker.get(point, ())
            if (n, None) in armed or (n, rank) in armed:
                return point
    return None


def check_gen_pressure() -> bool:
    """``gen_page_pressure``: True on an armed scheduler-tick occurrence
    (own counter — deliberately NOT the ``check_gen_step`` counter, so
    arming pressure never shifts the wedge/slow-step schedules). The
    *action* (claiming every free KV page and holding it for a few
    ticks to force decode page faults into the preemption path) belongs
    to the generation scheduler — this stays pure bookkeeping."""
    return enabled() and _fire_qualified(GEN_PAGE_PRESSURE, 0)


def check_collective(rank: int) -> bool:
    """``collective_skip``: True on an armed collective-op occurrence
    for trainer ``rank`` (``collective_skip@N:R`` = rank R's Nth
    collective; without ``:R`` any rank's Nth matches). The *action*
    (returning the input untouched, journaling nothing) belongs to the
    ``distributed/collective.py`` wrappers — this stays pure
    bookkeeping. Fires exactly once, so a retried operation replays
    clean."""
    return enabled() and _fire_qualified(COLLECTIVE_SKIP, rank)


def check_ps(rank: int = 0) -> Optional[str]:
    """Parameter-server points, evaluated once per request the
    :class:`~paddle1_tpu.distributed.ps_server.TableServer` handles.
    Kill and hang share one request counter (``ps_kill@N:R`` reads "on
    the Nth request of PS rank R"; without ``:R`` any server's Nth
    request matches); priority ``PS_KILL`` > ``PS_HANG`` when both arm
    the same request. The *action* (apply + checkpoint, then SIGKILL
    self before acking / stalling past the client timeout) is performed
    by ``distributed.ps_server`` — this stays pure bookkeeping."""
    if not _armed_worker:
        return None
    with _lock:
        n = _counters.get("ps_request", 0) + 1
        _counters["ps_request"] = n
        for point in _PS_POINTS:
            armed = _armed_worker.get(point, ())
            if (n, None) in armed or (n, rank) in armed:
                return point
    return None


def check_delta_corrupt() -> bool:
    """``delta_corrupt``: True on an armed delta-publish occurrence
    (own counter). The *action* (bit-flipping the committed payload so
    the subscriber's CRC verify must catch it) belongs to
    ``DeltaLog.publish`` — this stays pure bookkeeping."""
    return enabled() and _fire_qualified(DELTA_CORRUPT, 0)


def check_delta_gap() -> bool:
    """``delta_gap``: True on an armed delta-publish occurrence (own
    counter). The *action* (force-pruning every older delta from under
    a lagging reader, seeding the hole ``DeltaGapDetected`` must catch)
    belongs to ``DeltaLog.publish`` — this stays pure bookkeeping."""
    return enabled() and _fire_qualified(DELTA_GAP, 0)


def _fire_qualified(point: str, qualifier: int) -> bool:
    """Record one occurrence of a qualified (worker/loader) point on its
    own counter; True iff this occurrence is armed for ``qualifier`` (or
    unqualified)."""
    if not _armed_worker:
        return False
    with _lock:
        if point not in _armed_worker:
            return False
        n = _counters.get(point, 0) + 1
        _counters[point] = n
        armed = _armed_worker[point]
        return (n, None) in armed or (n, qualifier) in armed


def check_sample(worker: Optional[int] = None) -> None:
    """``corrupt_sample``: raise :class:`ChaosInjectedError` on an armed
    sample-fetch occurrence (the Nth ``dataset[i]`` / reader item of
    loader worker ``worker``; the single-process path is worker 0). The
    ``loader_bad_sample`` policy then treats it like any real corrupt
    record."""
    if enabled() and _fire_qualified(CORRUPT_SAMPLE,
                                     0 if worker is None else worker):
        raise ChaosInjectedError("chaos: corrupt sample record")


def check_loader_worker_kill(worker: int) -> bool:
    """``loader_worker_kill``: True on an armed task occurrence for
    loader worker ``worker``. The *action* (SIGKILL self) belongs to the
    worker loop — this stays pure bookkeeping."""
    return enabled() and _fire_qualified(LOADER_WORKER_KILL, worker)


def check_loader_stall(worker: int) -> bool:
    """``loader_stall``: True on an armed task/batch occurrence; the
    loader sleeps ``loader_chaos_stall_s`` (the input-stall watchdog's
    reproducible trigger)."""
    return enabled() and _fire_qualified(LOADER_STALL, worker)


def check_preempt() -> None:
    """``preempt``: raise :class:`SimulatedPreemption` on an armed step
    occurrence, or when :func:`request_preemption` was called."""
    global _preempt_requested
    if not enabled():
        return
    if _preempt_requested:
        _preempt_requested = False
        raise SimulatedPreemption("preemption requested", graceful=True)
    if fire(PREEMPT):
        raise SimulatedPreemption("chaos: simulated preemption")
