"""Runtime JIT-discipline sanitizer (ISSUE 12).

The static passes (``tools/lint/donation_safety.py``,
``retrace_hazard.py``, ``host_sync.py``) see lexical shapes; they
cannot see a donated buffer smuggled through a helper return, a
retrace storm driven by runtime shapes, or a readback three frames
down a hot loop. This module covers those dynamically, the
``core/locks.py`` way: one flag (``debug_jit_sanitizer``), structurally
zero cost off, typed errors on.

* **Retrace-storm enforcement** — the engines count distinct dispatch
  signatures (the ``jit_retrace_warn`` warn-once guard). Under the
  sanitizer, a site whose signature count exceeds its limit raises the
  typed :class:`RetraceStormError` instead of warning once and letting
  the host loop serialize behind the compiler — the warn upgraded to
  an enforceable invariant for the CI sanitizer lane.

* **Donated-buffer poisoning** — after a donating dispatch,
  :meth:`JitSite.poison_donated` records each donated ``jax.Array``
  and ``.delete()``-s it. On CPU (the test backend) donation silently
  no-ops — input and output are separate buffers — which is exactly
  why the PR 1 donation-aliasing bug passed every test: the poisoned
  delete makes ANY later use fail deterministically on every backend.
  A use reaching a guarded entry point (:meth:`JitSite.guard_args`)
  raises the typed :class:`UseAfterDonateError` *naming the donation
  site*; a use anywhere else fails with jax's own deleted-buffer
  error — loud either way, never silent corruption.

* **Host-sync counting** — :func:`note_host_sync` marks a real
  device→host readback (the ``async_loss`` materialization, the decode
  loop's token fetch — the ``note_blocking`` pattern retargeted).
  Under the sanitizer each event is counted, attributed to the
  innermost :func:`hot_section` the thread is in (the engine step
  loop, the batcher dispatch, the decode loop mark themselves). Tests
  assert sync *budgets* — "this loop pays exactly one readback per
  chunk" — instead of eyeballing profiles. Free when never armed: one
  module bool test.

Off (the default) is structurally free: :func:`site` returns ``None``
(engines hold a ``None`` attribute and skip one ``is not None`` test
per dispatch), :func:`wrap_donating` returns the function object
unchanged, and :func:`hot_section` hands back a shared no-op context
manager.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .errors import EnforceNotMet

__all__ = ["RetraceStormError", "UseAfterDonateError", "RETRACE_LIMIT",
           "sanitizing", "site", "JitSite", "wrap_donating",
           "hot_section", "note_host_sync", "host_sync_events",
           "host_sync_count", "reset"]


class RetraceStormError(EnforceNotMet):
    """One jit entry point compiled more distinct signatures than its
    limit — the silent host-loop serializer, made loud."""


class UseAfterDonateError(EnforceNotMet):
    """A buffer whose storage was donated to XLA re-entered a guarded
    dispatch — the PR 1 embedding-deletion shape, caught typed."""


# distinct signatures one site may compile before the storm is an
# error (generous: shape buckets are bounded by design — a site
# legitimately needing more passes an explicit limit to site())
RETRACE_LIMIT = 8

# flipped True the first time a site/hot_section arms — the only cost
# note_host_sync() pays in a process that never enabled the flag
_armed = False

_lock = threading.Lock()
# id(donated jax.Array) -> site name; consulted ONLY for arrays whose
# .is_deleted() is True (ids recycle after GC — deletion is the
# poison, the registry merely names the donation site in the error)
_donated: Dict[int, str] = {}
# (section, what) -> count of host-sync events
_sync_events: Dict[Tuple[str, str], int] = {}

_tls = threading.local()


def sanitizing() -> bool:
    """Whether the ``debug_jit_sanitizer`` flag is on (read per
    construction — hot paths hold the site object, not the flag)."""
    from . import flags as core_flags
    return bool(core_flags.flag("debug_jit_sanitizer"))


def reset() -> None:
    """Drop donated-buffer records and sync counters, and re-derive the
    armed latch from the CURRENT flag (test isolation: an armed test
    must not leave flag-off code counting — or paying the counter lock
    — for the rest of the process)."""
    global _armed
    with _lock:
        _donated.clear()
        _sync_events.clear()
    _armed = sanitizing()


class JitSite:
    """Per-entry-point sanitizer handle (engine step, decode, prefill).
    Constructed only when the flag is on — see :func:`site`."""

    __slots__ = ("name", "retrace_limit")

    def __init__(self, name: str, retrace_limit: int = RETRACE_LIMIT):
        self.name = name
        self.retrace_limit = int(retrace_limit)

    # -- retrace storms -----------------------------------------------------

    def note_signatures(self, n: int, kind: str = "",
                        limit: Optional[int] = None) -> None:
        """Record that this site has now seen ``n`` distinct dispatch
        signatures; raises typed when past the limit."""
        lim = self.retrace_limit if limit is None else int(limit)
        if n > lim:
            raise RetraceStormError(
                f"retrace storm at {self.name}"
                + (f" ({kind})" if kind else "")
                + f": {n} distinct jit signatures compiled (limit "
                f"{lim}) — every one is a full XLA compile silently "
                "re-serializing the host loop. Pad or bucket the "
                "varying dimension to a fixed set of shapes "
                "(serve_buckets / serve_gen_prefill_buckets are the "
                "serving knobs; pad batches for training). "
                "debug_jit_sanitizer upgraded the jit_retrace_warn "
                "warn-once to this error.")

    # -- donation poisoning -------------------------------------------------

    def guard_args(self, leaves: Iterable[Any],
                   what: str = "") -> None:
        """Raise typed if any argument leaf was poisoned by an earlier
        donating dispatch (the use-after-donate entry check)."""
        for leaf in leaves:
            is_deleted = getattr(leaf, "is_deleted", None)
            if is_deleted is None:
                continue
            try:
                dead = bool(is_deleted())
            except TypeError:  # pragma: no cover - exotic array type
                continue
            if dead:
                origin = _donated.get(id(leaf))
                raise UseAfterDonateError(
                    f"use-after-donate entering {self.name}"
                    + (f" ({what})" if what else "") + ": an argument "
                    "buffer was donated "
                    + (f"by {origin} " if origin else "")
                    + "in an earlier dispatch — its storage belongs "
                    "to XLA now (on CPU the donation silently no-ops, "
                    "which is how the PR 1 aliasing bug passed every "
                    "test). Rebind the variable from the dispatch "
                    "result, or copy before donating "
                    "(jnp.array(v, copy=True)).")

    def poison_donated(self, leaves: Iterable[Any]) -> None:
        """After a donating dispatch: delete each donated array so any
        later use fails deterministically (on TPU jax already deleted
        them — the delete is idempotent; on CPU, where donation
        no-ops, this closes the silent-corruption window)."""
        dead: List[Any] = []
        for leaf in leaves:
            if hasattr(leaf, "is_deleted") and hasattr(leaf, "delete"):
                dead.append(leaf)
        with _lock:
            for leaf in dead:
                _donated[id(leaf)] = self.name
                # keep the registry bounded: ids recycle anyway, the
                # names are best-effort forensics
                if len(_donated) > 4096:
                    _donated.clear()
                    _donated[id(leaf)] = self.name
        for leaf in dead:
            try:
                leaf.delete()
            except Exception:  # pragma: no cover - never break dispatch
                pass


def site(name: str,
         retrace_limit: int = RETRACE_LIMIT) -> Optional[JitSite]:
    """A :class:`JitSite` when ``debug_jit_sanitizer`` is on, else
    ``None`` — callers keep the result and gate on ``is not None``
    (one pointer test per dispatch; nothing off the flag path)."""
    global _armed
    if not sanitizing():
        return None
    _armed = True
    return JitSite(name, retrace_limit)


def wrap_donating(fn, donate_argnums: Tuple[int, ...], name: str,
                  retrace_limit: int = RETRACE_LIMIT):
    """Wrap a donating jit callable with the guard/poison pair. OFF:
    returns ``fn`` itself (the pass-through the zero-cost test pins).
    ON: every call checks all argument leaves for poisoned buffers,
    dispatches, then poisons the donated ones."""
    s = site(name, retrace_limit)
    if s is None:
        return fn

    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        import jax
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        s.guard_args(leaves, "wrapped call")
        donated = [leaf for i in donate_argnums if i < len(args)
                   for leaf in jax.tree_util.tree_leaves(args[i])]
        out = fn(*args, **kwargs)
        s.poison_donated(donated)
        return out

    wrapped.__wrapped__ = fn
    return wrapped


# -- hot sections + host-sync counting ---------------------------------------


class _NullSection:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSection()


def _sections() -> List[str]:
    s = getattr(_tls, "sections", None)
    if s is None:
        s = _tls.sections = []
    return s


class _HotSection:
    __slots__ = ("name", "_owner")

    def __init__(self, name: str):
        self.name = name
        self._owner: Optional[List[str]] = None

    def __enter__(self):
        # remember the OWNING thread's list: a generator-held section
        # (step_stream) can be finalized by another thread (GC), and
        # the marker must come off the list it went onto — not the
        # finalizer's, and never leak on the owner's
        self._owner = _sections()
        self._owner.append(self.name)
        return self

    def __exit__(self, *exc):
        s = self._owner if self._owner is not None else _sections()
        self._owner = None
        for i in range(len(s) - 1, -1, -1):
            if s[i] == self.name:
                del s[i]
                break
        return False


def hot_section(name: str):
    """Mark a latency-budgeted region (the runtime half of the lint
    pass's ``# hot-path`` marker). Shared no-op when the flag is off;
    on, host-sync events on this thread attribute to the innermost
    section."""
    global _armed
    if not sanitizing():
        return _NULL
    _armed = True
    return _HotSection(name)


def note_host_sync(what: str) -> None:
    """Mark one real device→host readback (async_loss materialization,
    decode token fetch). Counted under the sanitizer, attributed to the
    innermost hot section ('' outside one). Free when never armed: one
    module bool test."""
    if not _armed:
        return
    s = _sections()
    section = s[-1] if s else ""
    with _lock:
        key = (section, what)
        _sync_events[key] = _sync_events.get(key, 0) + 1


def host_sync_events() -> Dict[Tuple[str, str], int]:
    """Copy of the (section, what) -> count map (test hook)."""
    with _lock:
        return dict(_sync_events)


def host_sync_count(section: Optional[str] = None) -> int:
    """Total counted sync events, optionally for one section."""
    with _lock:
        return sum(n for (sec, _), n in _sync_events.items()
                   if section is None or sec == section)
