"""Device / Place abstraction.

Analog of the reference's Place variants and DeviceContextPool
(/root/reference/paddle/fluid/platform/place.h:26-95,
platform/device_context.h:107,795). On TPU the "device context" — streams,
library handles, per-device state — is owned by PJRT/XLA; Place here is a thin
identity wrapper over a ``jax.Device`` plus a process-global current-place,
which eager ops consult for output placement (the reference's
``DeviceContextPool::Get(place)`` pattern collapses into jax's default-device
machinery).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Union

import jax

from .errors import InvalidArgumentError, UnavailableError

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "set_device", "get_device",
    "device_guard", "is_compiled_with_tpu", "device_count",
]


class Place:
    """Device identity: (kind, index) resolving lazily to a jax.Device."""

    kind: str = "unknown"

    def __init__(self, index: int = 0):
        self.index = int(index)

    def _jax_backend(self) -> str:
        raise NotImplementedError

    def jax_device(self) -> jax.Device:
        try:
            devs = jax.devices(self._jax_backend())
        except RuntimeError as e:
            raise UnavailableError(
                f"No {self.kind} devices available: {e}") from None
        if self.index >= len(devs):
            raise InvalidArgumentError(
                f"{self.kind}:{self.index} out of range; "
                f"{len(devs)} device(s) present")
        return devs[self.index]

    def __eq__(self, other):
        return (isinstance(other, Place) and self.kind == other.kind
                and self.index == other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"


class CPUPlace(Place):
    kind = "cpu"

    def _jax_backend(self) -> str:
        return "cpu"


class TPUPlace(Place):
    """A single TPU chip/core. The reference's CUDAPlace analog."""
    kind = "tpu"

    def _jax_backend(self) -> str:
        # Under the experimental tunnel the platform may register as a
        # non-'tpu' name; fall back to the default backend.
        for name in ("tpu", "axon"):
            try:
                if jax.devices(name):
                    return name
            except RuntimeError:
                continue
        return jax.default_backend()


_tls = threading.local()


def _parse(device: Union[str, Place]) -> Place:
    if isinstance(device, Place):
        return device
    if not isinstance(device, str):
        raise InvalidArgumentError(f"Cannot parse device: {device!r}")
    dev = device.lower()
    if ":" in dev:
        kind, idx = dev.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = dev, 0
    if kind in ("cpu",):
        return CPUPlace(idx)
    if kind in ("tpu", "xla", "gpu", "accelerator"):  # gpu accepted for compat
        return TPUPlace(idx)
    raise InvalidArgumentError(f"Unknown device kind: {device!r}")


def set_device(device: Union[str, Place]) -> Place:
    place = _parse(device)
    _tls.place = place
    jax.config.update("jax_default_device", place.jax_device())
    return place


def get_device() -> Place:
    place = getattr(_tls, "place", None)
    if place is None:
        # Default: accelerator if present else CPU.
        backend = jax.default_backend()
        place = CPUPlace(0) if backend == "cpu" else TPUPlace(0)
        _tls.place = place
    return place


@contextlib.contextmanager
def device_guard(device: Union[str, Place]):
    """Scoped device switch (reference framework.py:6021 device_guard)."""
    prev = get_device()
    set_device(device)
    try:
        yield
    finally:
        set_device(prev)


def is_compiled_with_tpu() -> bool:
    try:
        return len(jax.devices()) > 0 and jax.default_backend() != "cpu"
    except RuntimeError:
        return False


def device_count() -> int:
    return jax.device_count()
