"""Eager Tensor.

Analog of the reference's dygraph ``VarBase``
(/root/reference/paddle/fluid/imperative/layer.h:66) + the Python method
patches (python/paddle/fluid/dygraph/math_op_patch.py,
varbase_patch_methods.py). A Tensor wraps a ``jax.Array`` plus autograd
metadata; every computation flows through ``paddle1_tpu.autograd.engine.apply``
which both executes the jax op and records a grad node (the reference's
``Tracer::TraceOp`` tracer.cc:133,207 collapses into that single function
because XLA owns kernel dispatch).

Paddle semantics preserved: ``stop_gradient`` defaults to True for plain
tensors and False for ``Parameter``; ``.backward()`` runs the tape engine;
``.grad`` is populated on leaves; hooks fire on gradient flow.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .errors import InvalidArgumentError, PreconditionNotMetError
from .place import Place, get_device

__all__ = ["Tensor", "to_tensor", "Parameter"]


def _as_array(data, dtype=None) -> jax.Array:
    if isinstance(data, Tensor):
        data = data.data
    from .indexed_slices import IndexedSlices
    if isinstance(data, IndexedSlices):
        # a Tensor may carry a row-sparse gradient (SelectedRows-typed
        # variable in the reference); consumers branch on isinstance
        return data if dtype is None else data.astype(
            dtypes.convert_dtype(dtype))
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        arr = data
        if dtype is not None:
            arr = arr.astype(dtypes.convert_dtype(dtype))
        return arr
    if isinstance(data, (np.ndarray, np.generic)):
        if dtype is None and data.dtype == np.float64:
            dtype = dtypes.get_default_dtype()  # numpy float64 → default f32
        return jnp.asarray(data, dtype=dtypes.convert_dtype(dtype) if dtype else None)
    if isinstance(data, (bool, int, float, complex)):
        if dtype is None:
            if isinstance(data, bool):
                dtype = dtypes.bool_
            elif isinstance(data, int):
                dtype = dtypes.int64
            else:
                dtype = dtypes.get_default_dtype()
        return jnp.asarray(data, dtype=dtypes.convert_dtype(dtype))
    if isinstance(data, (list, tuple)):
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            dtype = dtypes.get_default_dtype()
        return jnp.asarray(arr, dtype=dtypes.convert_dtype(dtype) if dtype else None)
    raise InvalidArgumentError(
        f"Cannot convert {type(data).__name__} to Tensor")


class Tensor:
    """Eager tensor with autograd metadata."""

    # Keep instances lightweight: these are created once per eager op output.
    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "_output_index",
                 "_hooks", "_retain_grad", "name", "persistable",
                 "__weakref__")

    def __init__(self, data, dtype=None, place: Optional[Place] = None,
                 stop_gradient: bool = True, name: Optional[str] = None):
        self._data = _as_array(data, dtype)
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._node = None            # GradNode that produced this tensor
        self._output_index = 0       # which output of that node
        self._hooks: List = []
        self._retain_grad = False
        self.name = name
        self.persistable = False

    # -- raw array access ---------------------------------------------------

    @property
    def data(self) -> jax.Array:
        return self._data

    @data.setter
    def data(self, value):
        self._data = _as_array(value)

    # -- metadata -----------------------------------------------------------

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.ndim else 1

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._data.dtype)

    @property
    def place(self) -> Place:
        return get_device()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    # -- conversion ---------------------------------------------------------

    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        if self.size != 1:
            raise InvalidArgumentError(
                f"item() requires a single-element tensor, got shape {self.shape}")
        return self._data.reshape(()).item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__()

    # -- autograd -----------------------------------------------------------

    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False) -> None:
        """Reverse-mode from this tensor (reference
        varbase_patch_methods.py:167 → BasicEngine)."""
        from ..autograd import engine
        engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        """Hook on this tensor's gradient during backward. Returns a handle
        with ``remove()`` (reference imperative/hooks.h semantics)."""
        if self.stop_gradient:
            raise PreconditionNotMetError(
                "Cannot register hook on a tensor with stop_gradient=True")
        entry = [hook]
        self._hooks.append(entry)

        class _Handle:
            def remove(_self):
                entry[0] = None
        return _Handle()

    def retain_grads(self) -> None:
        self._retain_grad = True

    def clear_grad(self) -> None:
        self._grad = None

    def clear_gradient(self) -> None:  # legacy alias
        self._grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def clone(self) -> "Tensor":
        from ..autograd.engine import apply
        return apply("clone", lambda x: x + jnp.zeros((), x.dtype), (self,))

    def _replace_impl(self, other: "Tensor") -> None:
        """In-place value replacement preserving identity (used by setitem
        and optimizer in-place updates)."""
        self._data = other._data
        self._node = other._node
        self._output_index = other._output_index

    # -- python protocol ----------------------------------------------------

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self) -> str:
        try:
            vals = np.array2string(self.numpy(), precision=6, threshold=40)
        except Exception:
            vals = "<traced>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={self.stop_gradient},\n       {vals})")

    def __bool__(self) -> bool:
        if self.size != 1:
            raise InvalidArgumentError(
                "The truth value of a multi-element Tensor is ambiguous")
        return bool(self._data)

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # Arithmetic/indexing methods are patched in by paddle1_tpu.ops.patch
    # (mirrors the reference's math_op_patch.py monkey-patching approach so
    # the op layer and tensor type stay decoupled).


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable, with a trainable
    flag (reference framework.py:5557 Parameter / :5663 ParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "sharding_axes", "pp_stage")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        # Per-dim mesh-axis names for pjit parameter sharding, e.g.
        # (None, "mp") shards dim 1 over the model-parallel axis. Consumed
        # by distributed.sharding_specs.collect_param_specs.
        self.sharding_axes = None
        # Pipeline stage this parameter belongs to (set by PipelineLayer).
        self.pp_stage = None

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor equivalent (reference fluid/dygraph/base.py:597
    to_variable + 2.0 creation API)."""
    if isinstance(data, Tensor):
        if dtype is not None and dtypes.convert_dtype(dtype) != data.dtype:
            data = Tensor(data.data, dtype=dtype, stop_gradient=stop_gradient)
            return data
        t = Tensor(data.data, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
