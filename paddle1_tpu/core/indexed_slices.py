"""IndexedSlices — the sparse-gradient representation.

TPU-native analog of the reference's ``SelectedRows``
(/root/reference/paddle/fluid/framework/selected_rows.h:34 — a {rows,
value, height} triple produced by lookup_table_grad and consumed by the
sparse optimizer kernels, e.g. adam_op.h's SelectedRows branch).

Design (SURVEY §7 hard part (e)): in **eager** mode an embedding backward
emits ``IndexedSlices(rows, values, dense_shape)`` whose memory is
O(touched_rows × dim) — independent of the vocabulary size. Gradient
accumulation concatenates slices lazily (the reference's
GradientAccumulator + MergeAdd protocol); optimizers either apply
row-sparse updates directly (``lazy_mode``) or densify. Under ``jit`` the
whole step is a fused XLA program where scatter-add *is* the efficient
lowering, so the functional path densifies by design — documented, not
accidental.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["IndexedSlices"]


class IndexedSlices:
    """A row-sparse tensor: ``values[i]`` is the slice for row ``rows[i]``
    of a dense tensor of shape ``dense_shape``. Duplicate rows are allowed
    (sum semantics) until :meth:`merge` coalesces them."""

    __slots__ = ("rows", "values", "dense_shape")

    def __init__(self, rows, values, dense_shape: Sequence[int]):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        values = jnp.asarray(values)
        self.values = values.reshape((self.rows.shape[0],) +
                                     tuple(dense_shape[1:]))
        self.dense_shape: Tuple[int, ...] = tuple(int(s) for s in dense_shape)

    # -- metadata (mirrors the dense Tensor surface used by the engine) ----

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.dense_shape

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def ndim(self) -> int:
        return len(self.dense_shape)

    @property
    def n_rows(self) -> int:
        """Number of stored (possibly duplicate) row slices."""
        return int(self.rows.shape[0])

    def astype(self, dtype) -> "IndexedSlices":
        return IndexedSlices(self.rows, self.values.astype(dtype),
                             self.dense_shape)

    # -- algebra -----------------------------------------------------------

    def __add__(self, other):
        if isinstance(other, IndexedSlices):
            if other.dense_shape != self.dense_shape:
                raise ValueError(
                    f"IndexedSlices shape mismatch: {self.dense_shape} vs "
                    f"{other.dense_shape}")
            return IndexedSlices(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.dense_shape)
        # dense + sparse → dense (the accumulation fallback)
        return self.add_to_dense(jnp.asarray(other))

    __radd__ = __add__

    def __mul__(self, scalar):
        return IndexedSlices(self.rows, self.values * scalar,
                             self.dense_shape)

    __rmul__ = __mul__

    def merge(self) -> "IndexedSlices":
        """Coalesce duplicate rows by summation (reference
        operators/math/selected_rows_functor.h MergeAdd). Host-side unique:
        merge runs on the eager path where rows are concrete."""
        rows = np.asarray(self.rows)
        uniq, inv = np.unique(rows, return_inverse=True)
        summed = jax.ops.segment_sum(self.values, jnp.asarray(inv),
                                     num_segments=int(uniq.shape[0]))
        return IndexedSlices(jnp.asarray(uniq, jnp.int32), summed,
                             self.dense_shape)

    def to_dense(self) -> jax.Array:
        return self.add_to_dense(
            jnp.zeros(self.dense_shape, self.values.dtype))

    def add_to_dense(self, dense: jax.Array) -> jax.Array:
        return dense.at[self.rows].add(
            self.values.astype(dense.dtype))

    def __repr__(self):
        return (f"IndexedSlices(n_rows={self.rows.shape[0]}, "
                f"dense_shape={self.dense_shape}, dtype={self.dtype})")
