"""Baseline JPEG codec in pure numpy.

Backs ``paddle.vision.ops.decode_jpeg`` (reference vision/ops.py
decode_jpeg over nvjpeg / operators/decode_jpeg_op.cu). The image has
no JPEG library (no PIL/cv2/torchvision), so the decoder is
implemented from the ITU-T.81 baseline process: marker parse → huffman
entropy decode → dequant → zigzag → 8x8 IDCT (exact DCT-III basis
matmul — an MXU-shaped contraction) → chroma upsample → YCbCr→RGB.
Sequential baseline DCT only (SOF0), the overwhelmingly common form
and the one the reference's nvjpeg path targets; progressive JPEGs
raise a teaching error. A matching encoder exists for tests and for
``encode_jpeg`` parity.
"""

from __future__ import annotations

import struct

import numpy as np

from .errors import InvalidArgumentError, UnimplementedError

__all__ = ["decode_jpeg_bytes", "encode_jpeg_bytes"]

_ZIGZAG = np.array([
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63])


def _dct_basis():
    k = np.arange(8)
    n = np.arange(8)
    M = np.cos((2 * n[None, :] + 1) * k[:, None] * np.pi / 16)
    M[0] *= 1 / np.sqrt(2)
    return M * 0.5  # orthonormal scale


_M = _dct_basis()


def _idct2(blocks):
    """[N, 8, 8] coefficient blocks → spatial (DCT-III both axes)."""
    return np.einsum("ky,nkl,lx->nyx", _M, blocks, _M)


def _fdct2(blocks):
    """Forward: B = M A Mᵀ (the einsum transposes of _idct2)."""
    return np.einsum("ky,nyx,lx->nkl", _M, blocks, _M)


class _BitReader:
    """MSB-first bit reader over the entropy-coded segment with JPEG
    0xFF00 byte unstuffing and restart-marker awareness."""

    def __init__(self, data, pos):
        self.data = data
        self.pos = pos
        self.bits = 0
        self.nbits = 0

    def _next_byte(self):
        d = self.data
        while True:
            b = int(d[self.pos])  # python int: uint8 overflows EXTEND
            self.pos += 1
            if b == 0xFF:
                if int(d[self.pos]) == 0x00:
                    self.pos += 1
                    return 0xFF
                # a marker: signal end of segment to the caller
                self.pos -= 1
                raise _MarkerHit()
            return b

    def read_bit(self):
        if self.nbits == 0:
            self.bits = self._next_byte()
            self.nbits = 8
        self.nbits -= 1
        return (self.bits >> self.nbits) & 1

    def receive(self, n):
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v

    def align(self):
        self.nbits = 0


class _MarkerHit(Exception):
    pass


def _extend(v, t):
    """T.81 EXTEND: map the t-bit magnitude to its signed value."""
    return v if v >= (1 << (t - 1)) else v - (1 << t) + 1


class _Huff:
    """Canonical JPEG huffman table → (code-length run) decoder."""

    def __init__(self, counts, symbols):
        self.lookup = {}
        code = 0
        k = 0
        for length in range(1, 17):
            for _ in range(counts[length - 1]):
                self.lookup[(length, code)] = symbols[k]
                k += 1
                code += 1
            code <<= 1

    def decode(self, br):
        code = 0
        for length in range(1, 17):
            code = (code << 1) | br.read_bit()
            sym = self.lookup.get((length, code))
            if sym is not None:
                return int(sym)  # numpy uint8 would overflow EXTEND
        raise InvalidArgumentError("corrupt JPEG: bad huffman code")


def decode_jpeg_bytes(data: bytes) -> np.ndarray:
    """Decode baseline JPEG bytes → [H, W, C] uint8 (C = 1 or 3)."""
    d = np.frombuffer(data, np.uint8)
    if d.size < 4 or d[0] != 0xFF or d[1] != 0xD8:
        raise InvalidArgumentError("not a JPEG (missing SOI)")
    pos = 2
    qt = {}
    huff_dc, huff_ac = {}, {}
    frame = None
    restart_interval = 0
    while pos < d.size:
        if d[pos] != 0xFF:
            pos += 1
            continue
        marker = d[pos + 1]
        pos += 2
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            continue
        if marker == 0xD9:  # EOI
            break
        seg_len = struct.unpack(">H", d[pos:pos + 2].tobytes())[0]
        seg = d[pos + 2:pos + seg_len]
        if marker == 0xDB:  # DQT
            i = 0
            while i < seg.size:
                pq, tq = seg[i] >> 4, seg[i] & 0xF
                i += 1
                if pq:
                    tbl = d[pos + 2 + i:pos + 2 + i + 128].view(">u2")
                    i += 128
                else:
                    tbl = seg[i:i + 64]
                    i += 64
                qt[tq] = np.asarray(tbl, np.float64)
        elif marker in (0xC0, 0xC1):  # SOF0/1 baseline
            precision = seg[0]
            h = struct.unpack(">H", seg[1:3].tobytes())[0]
            w = struct.unpack(">H", seg[3:5].tobytes())[0]
            nc = int(seg[5])
            comps = []
            for c in range(nc):
                cid = int(seg[6 + 3 * c])
                hv = int(seg[7 + 3 * c])
                comps.append({"id": cid, "h": hv >> 4, "v": hv & 0xF,
                              "q": int(seg[8 + 3 * c])})
            frame = {"h": h, "w": w, "comps": comps,
                     "precision": precision}
        elif marker in (0xC2, 0xC3, 0xC5, 0xC6, 0xC7, 0xC9, 0xCA,
                        0xCB, 0xCD, 0xCE, 0xCF):
            raise UnimplementedError(
                "decode_jpeg: only baseline sequential DCT (SOF0/1) is "
                "implemented; this file uses a progressive/extended "
                "process")
        elif marker == 0xC4:  # DHT
            i = 0
            while i < seg.size:
                tc, th = seg[i] >> 4, seg[i] & 0xF
                counts = seg[i + 1:i + 17]
                n = int(counts.sum())
                symbols = seg[i + 17:i + 17 + n]
                tbl = _Huff(list(counts), list(symbols))
                (huff_dc if tc == 0 else huff_ac)[th] = tbl
                i += 17 + n
        elif marker == 0xDD:  # DRI
            restart_interval = struct.unpack(
                ">H", seg[:2].tobytes())[0]
        elif marker == 0xDA:  # SOS — entropy data follows
            ns = int(seg[0])
            scan = []
            for c in range(ns):
                cid = int(seg[1 + 2 * c])
                tt = int(seg[2 + 2 * c])
                comp = next(cc for cc in frame["comps"]
                            if cc["id"] == cid)
                scan.append({"comp": comp, "dc": tt >> 4,
                             "ac": tt & 0xF})
            data_start = pos + seg_len
            return _decode_scan(d, data_start, frame, scan, qt,
                                huff_dc, huff_ac, restart_interval)
        pos += seg_len
    raise InvalidArgumentError("corrupt JPEG: no scan data")


def _decode_scan(d, pos, frame, scan, qt, huff_dc, huff_ac,
                 restart_interval):
    h, w = frame["h"], frame["w"]
    hmax = max(c["h"] for c in frame["comps"])
    vmax = max(c["v"] for c in frame["comps"])
    mcus_x = -(-w // (8 * hmax))
    mcus_y = -(-h // (8 * vmax))
    planes = {}
    for sc in scan:
        c = sc["comp"]
        planes[c["id"]] = np.zeros(
            (mcus_y * c["v"] * 8, mcus_x * c["h"] * 8), np.float64)
    br = _BitReader(d, pos)
    pred = {sc["comp"]["id"]: 0 for sc in scan}
    mcu_count = 0
    for my in range(mcus_y):
        for mx in range(mcus_x):
            if restart_interval and mcu_count and \
                    mcu_count % restart_interval == 0:
                br.align()
                # skip the RSTn marker
                while d[br.pos] != 0xFF:
                    br.pos += 1
                br.pos += 2
                pred = {k: 0 for k in pred}
            for sc in scan:
                c = sc["comp"]
                for by in range(c["v"]):
                    for bx in range(c["h"]):
                        blk = _decode_block(
                            br, huff_dc[sc["dc"]], huff_ac[sc["ac"]],
                            pred, c["id"], qt[c["q"]])
                        y0 = (my * c["v"] + by) * 8
                        x0 = (mx * c["h"] + bx) * 8
                        planes[c["id"]][y0:y0 + 8, x0:x0 + 8] = blk
            mcu_count += 1
    # upsample + color transform
    out = []
    for sc in scan:
        c = sc["comp"]
        p = planes[c["id"]]
        ry, rx = vmax // c["v"], hmax // c["h"]
        if ry > 1 or rx > 1:
            p = np.repeat(np.repeat(p, ry, axis=0), rx, axis=1)
        out.append(p[:h, :w])
    if len(out) == 1:
        y = np.clip(out[0] + 128, 0, 255)
        return y[..., None].astype(np.uint8)
    y, cb, cr = out[0] + 128, out[1], out[2]
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    return np.clip(np.stack([r, g, b], axis=-1), 0,
                   255).astype(np.uint8)


def _decode_block(br, hdc, hac, pred, cid, qtbl):
    coef = np.zeros(64, np.float64)
    try:
        t = hdc.decode(br)
        diff = _extend(br.receive(t), t) if t else 0
        pred[cid] += diff
        coef[0] = pred[cid]
        k = 1
        while k < 64:
            rs = hac.decode(br)
            r, s = rs >> 4, rs & 0xF
            if s == 0:
                if r == 15:
                    k += 16
                    continue
                break  # EOB
            k += r
            if k > 63:
                break
            coef[k] = _extend(br.receive(s), s)
            k += 1
    except _MarkerHit:
        pass
    dq = coef * qtbl
    block = np.zeros(64, np.float64)
    block[_ZIGZAG] = dq
    return _idct2(block.reshape(1, 8, 8))[0]


# -- encoder (tests + encode parity) ----------------------------------------

_STD_LUM_Q = np.array([
    16, 11, 10, 16, 24, 40, 51, 61, 12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56, 14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77, 24, 35, 55, 64, 81, 104, 113,
    92, 49, 64, 78, 87, 103, 121, 120, 101, 72, 92, 95, 98, 112, 100,
    103, 99], np.float64)

# K.3.3 default luminance huffman specs
_DC_COUNTS = [0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
_DC_SYMS = list(range(12))
_AC_COUNTS = [0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7D]
_AC_SYMS = [
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12, 0x21, 0x31, 0x41,
    0x06, 0x13, 0x51, 0x61, 0x07, 0x22, 0x71, 0x14, 0x32, 0x81, 0x91,
    0xA1, 0x08, 0x23, 0x42, 0xB1, 0xC1, 0x15, 0x52, 0xD1, 0xF0, 0x24,
    0x33, 0x62, 0x72, 0x82, 0x09, 0x0A, 0x16, 0x17, 0x18, 0x19, 0x1A,
    0x25, 0x26, 0x27, 0x28, 0x29, 0x2A, 0x34, 0x35, 0x36, 0x37, 0x38,
    0x39, 0x3A, 0x43, 0x44, 0x45, 0x46, 0x47, 0x48, 0x49, 0x4A, 0x53,
    0x54, 0x55, 0x56, 0x57, 0x58, 0x59, 0x5A, 0x63, 0x64, 0x65, 0x66,
    0x67, 0x68, 0x69, 0x6A, 0x73, 0x74, 0x75, 0x76, 0x77, 0x78, 0x79,
    0x7A, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89, 0x8A, 0x92, 0x93,
    0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9A, 0xA2, 0xA3, 0xA4, 0xA5,
    0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4, 0xB5, 0xB6, 0xB7,
    0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5, 0xC6, 0xC7, 0xC8, 0xC9,
    0xCA, 0xD2, 0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA, 0xE1,
    0xE2, 0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA, 0xF1, 0xF2,
    0xF3, 0xF4, 0xF5, 0xF6, 0xF7, 0xF8, 0xF9, 0xFA]


def _huff_codes(counts, symbols):
    codes = {}
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(counts[length - 1]):
            codes[symbols[k]] = (length, code)
            k += 1
            code += 1
        code <<= 1
    return codes


class _BitWriter:
    def __init__(self):
        self.out = bytearray()
        self.acc = 0
        self.n = 0

    def write(self, length, code):
        for i in range(length - 1, -1, -1):
            self.acc = (self.acc << 1) | ((code >> i) & 1)
            self.n += 1
            if self.n == 8:
                self.out.append(self.acc)
                if self.acc == 0xFF:
                    self.out.append(0x00)  # byte stuffing
                self.acc = 0
                self.n = 0

    def flush(self):
        while self.n:
            self.write(1, 1)  # pad with 1s per T.81


def _category(v):
    a = abs(int(v))
    t = 0
    while a:
        a >>= 1
        t += 1
    return t


def encode_jpeg_bytes(img: np.ndarray, quality: int = 75) -> bytes:
    """Encode [H, W, 1|3] uint8 → baseline JPEG (4:4:4, shared
    luminance tables — a simple, spec-valid encoder for tests and
    encode parity)."""
    img = np.asarray(img, np.uint8)
    if img.ndim == 2:
        img = img[..., None]
    H, W, C = img.shape
    scale = (5000 / quality if quality < 50 else 200 - 2 * quality) \
        / 100.0
    q = np.clip(np.round(_STD_LUM_Q * scale), 1, 255)
    if C == 3:
        r, g, b = (img[..., i].astype(np.float64) for i in range(3))
        y = 0.299 * r + 0.587 * g + 0.114 * b - 128
        cb = -0.168736 * r - 0.331264 * g + 0.5 * b
        cr = 0.5 * r - 0.418688 * g - 0.081312 * b
        planes = [y, cb, cr]
    else:
        planes = [img[..., 0].astype(np.float64) - 128]
    dcc = _huff_codes(_DC_COUNTS, _DC_SYMS)
    acc_ = _huff_codes(_AC_COUNTS, _AC_SYMS)
    bw = _BitWriter()
    # pad planes to 8
    ph = -(-H // 8) * 8
    pw = -(-W // 8) * 8
    padded = []
    for p in planes:
        pp = np.zeros((ph, pw))
        pp[:H, :W] = p
        pp[H:, :W] = p[-1:, :]
        pp[:, W:] = pp[:, W - 1:W]
        padded.append(pp)
    pred = [0] * len(planes)
    for by in range(ph // 8):
        for bx in range(pw // 8):
            for ci, p in enumerate(padded):
                blk = p[by * 8:(by + 1) * 8, bx * 8:(bx + 1) * 8]
                coef = _fdct2(blk[None])[0].reshape(64)
                # zigzag-ordered quantization (q is stored zigzag in
                # DQT, matching the decoder's direct multiply)
                zz = np.round(coef[_ZIGZAG] / q).astype(np.int64)
                diff = int(zz[0]) - pred[ci]
                pred[ci] = int(zz[0])
                t = _category(diff)
                bw.write(dcc[t][0], dcc[t][1])
                if t:
                    mag = diff if diff >= 0 else diff + (1 << t) - 1
                    bw.write(t, mag & ((1 << t) - 1))
                run = 0
                last_nz = 0
                for k in range(1, 64):
                    if zz[k]:
                        last_nz = k
                for k in range(1, last_nz + 1):
                    v = int(zz[k])
                    if v == 0:
                        run += 1
                        continue
                    while run > 15:
                        bw.write(acc_[0xF0][0], acc_[0xF0][1])
                        run -= 16
                    s = _category(v)
                    sym = (run << 4) | s
                    bw.write(acc_[sym][0], acc_[sym][1])
                    mag = v if v >= 0 else v + (1 << s) - 1
                    bw.write(s, mag & ((1 << s) - 1))
                    run = 0
                if last_nz < 63:
                    bw.write(acc_[0x00][0], acc_[0x00][1])  # EOB
    bw.flush()

    def seg(marker, payload):
        return bytes([0xFF, marker]) + struct.pack(
            ">H", len(payload) + 2) + payload
    out = bytearray(b"\xff\xd8")
    out += seg(0xDB, bytes([0]) + bytes(q.astype(np.uint8)))
    nc = len(planes)
    sof = bytes([8]) + struct.pack(">HH", H, W) + bytes([nc])
    for c in range(nc):
        sof += bytes([c + 1, 0x11, 0])
    out += seg(0xC0, sof)
    out += seg(0xC4, bytes([0x00] + _DC_COUNTS) + bytes(_DC_SYMS))
    out += seg(0xC4, bytes([0x10] + _AC_COUNTS) + bytes(_AC_SYMS))
    sos = bytes([nc])
    for c in range(nc):
        sos += bytes([c + 1, 0x00])
    sos += bytes([0, 63, 0])
    out += seg(0xDA, sos)
    out += bw.out
    out += b"\xff\xd9"
    return bytes(out)
