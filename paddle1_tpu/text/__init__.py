"""paddle1_tpu.text (reference python/paddle/text analog) plus the BERT/
ERNIE model zoo (BASELINE.md configs 3/4)."""

from . import models

__all__ = ["models"]
