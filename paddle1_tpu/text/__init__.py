"""paddle1_tpu.text (reference python/paddle/text analog) plus the BERT/
ERNIE model zoo (BASELINE.md configs 3/4)."""

from . import models
from .datasets import (Conll05st, FakeTextDataset, Imdb, Imikolov,
                       Movielens, UCIHousing, WMT14, WMT16)

__all__ = ["models", "Imdb", "Imikolov", "Movielens", "Conll05st",
           "UCIHousing", "WMT14", "WMT16", "FakeTextDataset"]
