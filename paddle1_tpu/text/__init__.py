"""paddle1_tpu.text (reference python/paddle/text analog).

NLP datasets/building blocks land with the BERT config (stage 6).
"""

__all__ = []
