"""Text datasets (reference python/paddle/text/datasets/: Imdb, Conll05,
Movielens, UCIHousing, WMT14/16...). No network egress: parsers read the
official archive formats from a local path; ``FakeTextDataset`` generates
deterministic synthetic corpora so pipelines run hermetically."""

from __future__ import annotations

import gzip
import io
import os
import re
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "UCIHousing", "FakeTextDataset", "mlm_masking"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download unavailable (no network egress); "
        "pass data_file= pointing at the official archive.")


class Imdb(Dataset):
    """aclImdb sentiment archive parser (reference text/datasets/imdb.py).
    Yields (ids, label); tokenization via a caller-provided tokenizer or
    whitespace fallback."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 tokenizer=None):
        if data_file is None:
            _no_download("Imdb")
        self.mode = mode
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        self._docs, self._labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                text = tf.extractfile(m).read().decode("utf-8",
                                                       "ignore").lower()
                self._docs.append(text)
                self._labels.append(0 if match.group(1) == "neg" else 1)
        if tokenizer is None:
            from .tokenizer import BasicTokenizer, build_vocab
            basic = BasicTokenizer()
            self._vocab = build_vocab(self._docs, max_size=cutoff * 100)
            self._tok = lambda t: [self._vocab.get(w, 1)
                                   for w in basic.tokenize(t)]
        else:
            self._tok = lambda t: tokenizer.convert_tokens_to_ids(
                tokenizer.tokenize(t))

    def __getitem__(self, idx):
        ids = np.asarray(self._tok(self._docs[idx]), np.int64)
        return ids, np.array([self._labels[idx]], np.int64)

    def __len__(self):
        return len(self._docs)


class UCIHousing(Dataset):
    """housing.data whitespace table (reference text/datasets/
    uci_housing.py): 13 features, 1 target, feature-normalized."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is None:
            _no_download("UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        mean, std = feats.mean(0), feats.std(0) + 1e-8
        feats = (feats - mean) / std
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], target[:n_train]
        else:
            self.x, self.y = feats[n_train:], target[n_train:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class FakeTextDataset(Dataset):
    """Deterministic synthetic token sequences for LM/classification
    pipelines (the hermetic-test analog of FakeData)."""

    def __init__(self, num_samples=256, seq_len=64, vocab_size=1000,
                 num_classes=2, task="classify", seed=0,
                 mask_token_id=4, pad_token_id=0):
        rng = np.random.default_rng(seed)
        self.task = task
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.mask_token_id = mask_token_id
        self._ids = rng.integers(5, vocab_size, (num_samples, seq_len)
                                 ).astype(np.int32)
        self._labels = rng.integers(0, num_classes,
                                    num_samples).astype(np.int64)
        self._rng_seed = seed

    def __getitem__(self, idx):
        ids = self._ids[idx]
        if self.task == "classify":
            return ids, np.array([self._labels[idx]], np.int64)
        # mlm: mask 15% and return (masked_ids, labels with -1 off-mask)
        masked, labels = mlm_masking(ids, self.vocab_size,
                                     mask_token_id=self.mask_token_id,
                                     seed=self._rng_seed + idx)
        return masked, labels

    def __len__(self):
        return len(self._ids)


def mlm_masking(ids, vocab_size, mask_prob=0.15, mask_token_id=4,
                seed=0):
    """BERT masking recipe: of the selected 15%, 80% → [MASK], 10% →
    random token, 10% kept; labels are -1 everywhere else."""
    rng = np.random.default_rng(seed)
    ids = np.asarray(ids)
    sel = rng.random(ids.shape) < mask_prob
    labels = np.where(sel, ids, -1).astype(np.int32)
    r = rng.random(ids.shape)
    masked = ids.copy()
    masked[sel & (r < 0.8)] = mask_token_id
    rand_sel = sel & (r >= 0.8) & (r < 0.9)
    masked[rand_sel] = rng.integers(5, vocab_size,
                                    rand_sel.sum()).astype(ids.dtype)
    return masked, labels
