"""Text datasets (reference python/paddle/text/datasets/: Imdb, Conll05,
Movielens, UCIHousing, WMT14/16...). No network egress: parsers read the
official archive formats from a local path; ``FakeTextDataset`` generates
deterministic synthetic corpora so pipelines run hermetically."""

from __future__ import annotations

import gzip
import io
import os
import re
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "UCIHousing", "FakeTextDataset", "mlm_masking"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download unavailable (no network egress); "
        "pass data_file= pointing at the official archive.")


class Imdb(Dataset):
    """aclImdb sentiment archive parser (reference text/datasets/imdb.py).
    Yields (ids, label); tokenization via a caller-provided tokenizer or
    whitespace fallback."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 tokenizer=None):
        if data_file is None:
            _no_download("Imdb")
        self.mode = mode
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        self._docs, self._labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                text = tf.extractfile(m).read().decode("utf-8",
                                                       "ignore").lower()
                self._docs.append(text)
                self._labels.append(0 if match.group(1) == "neg" else 1)
        if tokenizer is None:
            from .tokenizer import BasicTokenizer, build_vocab
            basic = BasicTokenizer()
            self._vocab = build_vocab(self._docs, max_size=cutoff * 100)
            self._tok = lambda t: [self._vocab.get(w, 1)
                                   for w in basic.tokenize(t)]
        else:
            self._tok = lambda t: tokenizer.convert_tokens_to_ids(
                tokenizer.tokenize(t))

    def __getitem__(self, idx):
        ids = np.asarray(self._tok(self._docs[idx]), np.int64)
        return ids, np.array([self._labels[idx]], np.int64)

    def __len__(self):
        return len(self._docs)


class UCIHousing(Dataset):
    """housing.data whitespace table (reference text/datasets/
    uci_housing.py): 13 features, 1 target, feature-normalized."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is None:
            _no_download("UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        mean, std = feats.mean(0), feats.std(0) + 1e-8
        feats = (feats - mean) / std
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], target[:n_train]
        else:
            self.x, self.y = feats[n_train:], target[n_train:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class FakeTextDataset(Dataset):
    """Deterministic synthetic token sequences for LM/classification
    pipelines (the hermetic-test analog of FakeData)."""

    def __init__(self, num_samples=256, seq_len=64, vocab_size=1000,
                 num_classes=2, task="classify", seed=0,
                 mask_token_id=4, pad_token_id=0):
        rng = np.random.default_rng(seed)
        self.task = task
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.mask_token_id = mask_token_id
        self._ids = rng.integers(5, vocab_size, (num_samples, seq_len)
                                 ).astype(np.int32)
        self._labels = rng.integers(0, num_classes,
                                    num_samples).astype(np.int64)
        self._rng_seed = seed

    def __getitem__(self, idx):
        ids = self._ids[idx]
        if self.task == "classify":
            return ids, np.array([self._labels[idx]], np.int64)
        # mlm: mask 15% and return (masked_ids, labels with -1 off-mask)
        masked, labels = mlm_masking(ids, self.vocab_size,
                                     mask_token_id=self.mask_token_id,
                                     seed=self._rng_seed + idx)
        return masked, labels

    def __len__(self):
        return len(self._ids)


def mlm_masking(ids, vocab_size, mask_prob=0.15, mask_token_id=4,
                seed=0):
    """BERT masking recipe: of the selected 15%, 80% → [MASK], 10% →
    random token, 10% kept; labels are -1 everywhere else."""
    rng = np.random.default_rng(seed)
    ids = np.asarray(ids)
    sel = rng.random(ids.shape) < mask_prob
    labels = np.where(sel, ids, -1).astype(np.int32)
    r = rng.random(ids.shape)
    masked = ids.copy()
    masked[sel & (r < 0.8)] = mask_token_id
    rand_sel = sel & (r >= 0.8) & (r < 0.9)
    masked[rand_sel] = rng.integers(5, vocab_size,
                                    rand_sel.sum()).astype(ids.dtype)
    return masked, labels


class Imikolov(Dataset):
    """PTB language-model dataset (reference text/datasets/imikolov.py):
    builds the word dict from train+valid with a frequency cutoff
    (sorted by (-freq, word), ``<unk>`` last), then yields NGRAM windows
    or SEQ (src, trg) pairs over ``<s>``/``<e>``-wrapped sentences."""

    _TRAIN = "./simple-examples/data/ptb.train.txt"
    _VALID = "./simple-examples/data/ptb.valid.txt"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50):
        if data_file is None:
            _no_download("Imikolov")
        data_type = data_type.upper()
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        if data_type == "NGRAM" and window_size < 2:
            raise ValueError("NGRAM needs window_size >= 2")
        import collections
        with tarfile.open(data_file) as tf:
            def lines(name):
                return [ln.decode("utf-8", "ignore")
                        for ln in tf.extractfile(name).read().splitlines()]
            train, valid = lines(self._TRAIN), lines(self._VALID)
        freq = collections.defaultdict(int)
        for corpus in (train, valid):
            for ln in corpus:
                for w in ln.strip().split():
                    freq[w] += 1
                freq["<s>"] += 1
                freq["<e>"] += 1
        freq.pop("<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items()
                       if c > min_word_freq), key=lambda x: (-x[1], x[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        corpus = train if mode == "train" else valid
        self.data = []
        for ln in corpus:
            toks = ["<s>"] + ln.strip().split() + ["<e>"]
            ids = [self.word_idx.get(w, unk) for w in toks]
            if data_type == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(tuple(ids[i - window_size:i]))
            else:
                if len(ids) > 2:
                    self.data.append((ids[:-1], ids[1:]))

    def __getitem__(self, idx):
        return tuple(np.asarray(d, np.int64) for d in self.data[idx]) \
            if isinstance(self.data[idx][0], list) \
            else np.asarray(self.data[idx], np.int64)

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """ml-1m ratings (reference text/datasets/movielens.py): parses
    movies.dat/users.dat/ratings.dat (``::``-separated, latin-1) and
    yields (movie_id, category_ids, title_ids, user_id, gender, age,
    job, rating) as arrays."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        if data_file is None:
            _no_download("Movielens")
        if mode not in ("train", "test"):
            raise ValueError("mode must be train or test")
        import zipfile
        cat_dict, title_vocab = {}, {}
        movies, users = {}, {}
        with zipfile.ZipFile(data_file) as zf:
            root = "ml-1m"
            def lines(name):
                return zf.read(f"{root}/{name}").decode(
                    "latin-1").splitlines()
            for ln in lines("movies.dat"):
                if not ln.strip():
                    continue
                mid, title, cats = ln.strip().split("::")
                tids = []
                for w in title.split():
                    tids.append(title_vocab.setdefault(w,
                                                       len(title_vocab)))
                cids = []
                for c in cats.split("|"):
                    cids.append(cat_dict.setdefault(c, len(cat_dict)))
                movies[int(mid)] = (cids, tids)
            for ln in lines("users.dat"):
                if not ln.strip():
                    continue
                uid, gender, age, job = ln.strip().split("::")[:4]
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                   int(job))
            self.data = []
            rng = np.random.default_rng(rand_seed)
            for ln in lines("ratings.dat"):
                if not ln.strip():
                    continue
                uid, mid, rating = ln.strip().split("::")[:3]
                uid, mid = int(uid), int(mid)
                if mid not in movies or uid not in users:
                    continue
                is_test = rng.random() < test_ratio
                if (mode == "test") != is_test:
                    continue
                cids, tids = movies[mid]
                g, a, j = users[uid]
                self.data.append((mid, cids, tids, uid, g, a, j,
                                  float(rating)))
        self.categories_dict = cat_dict
        self.movie_title_dict = title_vocab

    def __getitem__(self, idx):
        mid, cids, tids, uid, g, a, j, r = self.data[idx]
        return (np.array([mid], np.int64), np.asarray(cids, np.int64),
                np.asarray(tids, np.int64), np.array([uid], np.int64),
                np.array([g], np.int64), np.array([a], np.int64),
                np.array([j], np.int64), np.array([r], np.float32))

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference text/datasets/conll05.py):
    aligned words/props member files, one (sentence, predicate, labels)
    sample per predicate column, props brackets converted to B-/I-/O
    tags."""

    _WORDS = "conll05st-release/test.wsj/words/test.wsj.words.gz"
    _PROPS = "conll05st-release/test.wsj/props/test.wsj.props.gz"

    def __init__(self, data_file=None):
        if data_file is None:
            _no_download("Conll05st")
        with tarfile.open(data_file) as tf:
            words_txt = gzip.decompress(
                tf.extractfile(self._WORDS).read()).decode("utf-8")
            props_txt = gzip.decompress(
                tf.extractfile(self._PROPS).read()).decode("utf-8")
        self.sentences, self.predicates, self.labels = [], [], []
        w_sents = self._split_sents(words_txt)
        p_sents = self._split_sents(props_txt)
        for words, props in zip(w_sents, p_sents):
            toks = [w.split()[0] for w in words]
            cols = [p.split() for p in props]
            lemmas = [c[0] for c in cols]
            n_preds = len(cols[0]) - 1
            for k in range(n_preds):
                brackets = [c[k + 1] for c in cols]
                tags = self._to_bio(brackets)
                pred_rows = [i for i, t in enumerate(tags)
                             if t.endswith("-V")]
                pred = lemmas[pred_rows[0]] if pred_rows else "-"
                self.sentences.append(toks)
                self.predicates.append(pred)
                self.labels.append(tags)
        self.word_dict = self._vocab(w for s in self.sentences for w in s)
        self.predicate_dict = self._vocab(self.predicates)
        self.label_dict = self._vocab(t for ts in self.labels for t in ts)

    @staticmethod
    def _split_sents(text):
        sents, cur = [], []
        for ln in text.splitlines():
            if ln.strip():
                cur.append(ln.strip())
            elif cur:
                sents.append(cur)
                cur = []
        if cur:
            sents.append(cur)
        return sents

    @staticmethod
    def _to_bio(brackets):
        tags, role = [], None
        for b in brackets:
            b = b.strip()
            opened = None
            if "(" in b:
                opened = b[b.index("(") + 1:].split("*")[0]
            if opened is not None:
                tags.append(f"B-{opened}")
                role = opened
            elif role is not None:
                tags.append(f"I-{role}")
            else:
                tags.append("O")
            if ")" in b:
                role = None
        return tags

    @staticmethod
    def _vocab(items):
        out = {}
        for it in items:
            out.setdefault(it, len(out))
        return out

    def __getitem__(self, idx):
        words = np.asarray([self.word_dict[w]
                            for w in self.sentences[idx]], np.int64)
        pred = np.array([self.predicate_dict[self.predicates[idx]]],
                        np.int64)
        labels = np.asarray([self.label_dict[t]
                             for t in self.labels[idx]], np.int64)
        return words, pred, labels

    def __len__(self):
        return len(self.sentences)


class _WMTBase(Dataset):
    _BOS, _EOS, _UNK = "<s>", "<e>", "<unk>"

    def _encode(self, pairs, src_dict, trg_dict):
        for d, side in ((src_dict, "src"), (trg_dict, "trg")):
            missing = [t for t in (self._BOS, self._EOS, self._UNK)
                       if t not in d]
            if missing:
                raise ValueError(
                    f"{side} dict lacks special tokens {missing} — "
                    f"dict_size must cover <s>/<e>/<unk> (>= 3) and the "
                    f"dict file must begin with them")
        bos, eos = trg_dict[self._BOS], trg_dict[self._EOS]
        sunk, tunk = src_dict[self._UNK], trg_dict[self._UNK]
        self.data = []
        for src, trg in pairs:
            s = [src_dict.get(w, sunk) for w in src]
            t = [trg_dict.get(w, tunk) for w in trg]
            self.data.append((s, [bos] + t, t + [eos]))

    def __getitem__(self, idx):
        return tuple(np.asarray(d, np.int64) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """WMT14 en→fr (reference text/datasets/wmt14.py): archive carries
    src.dict/trg.dict (one word per line, first dict_size used) and
    train/test members of tab-separated sentence pairs; yields
    (src_ids, <s>+trg_ids, trg_ids+<e>)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        if data_file is None:
            _no_download("WMT14")
        if mode not in ("train", "test", "gen"):
            raise ValueError("mode must be train/test/gen")
        if dict_size <= 0:
            raise ValueError("dict_size must be positive")
        with tarfile.open(data_file) as tf:
            src_dict = trg_dict = None
            pairs = []
            want = mode
            for m in tf.getmembers():
                if m.name.endswith("src.dict"):
                    src_dict = self._read_dict(tf.extractfile(m),
                                               dict_size)
                elif m.name.endswith("trg.dict"):
                    trg_dict = self._read_dict(tf.extractfile(m),
                                               dict_size)
                elif f"{want}/{want}" in m.name and m.isfile():
                    for ln in tf.extractfile(m).read().decode(
                            "utf-8", "ignore").splitlines():
                        parts = ln.split("\t")
                        if len(parts) >= 2:
                            pairs.append((parts[0].split(),
                                          parts[1].split()))
        if src_dict is None or trg_dict is None:
            raise ValueError("archive lacks src.dict/trg.dict members")
        self.src_ids, self.trg_ids = src_dict, trg_dict
        self._encode(pairs, src_dict, trg_dict)

    @staticmethod
    def _read_dict(fd, size):
        out = {}
        for i, ln in enumerate(fd.read().decode("utf-8",
                                                "ignore").splitlines()):
            if i >= size:
                break
            out[ln.strip()] = i
        return out


class WMT16(_WMTBase):
    """WMT16 en↔de (reference text/datasets/wmt16.py): tab-separated
    pair files wmt16/{train,val,test}; dictionaries are built from the
    TRAIN split with a size cap (reference builds and caches them the
    same way), special tokens first."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en"):
        if data_file is None:
            _no_download("WMT16")
        if mode not in ("train", "val", "test"):
            raise ValueError("mode must be train/val/test")
        if src_dict_size <= 0 or trg_dict_size <= 0:
            raise ValueError("dict sizes must be positive")
        src_col, trg_col = (0, 1) if lang == "en" else (1, 0)

        def read_pairs(tf, which):
            pairs = []
            for m in tf.getmembers():
                if m.name.endswith(f"wmt16/{which}") and m.isfile():
                    for ln in tf.extractfile(m).read().decode(
                            "utf-8", "ignore").splitlines():
                        parts = ln.split("\t")
                        if len(parts) >= 2:
                            pairs.append((parts[src_col].split(),
                                          parts[trg_col].split()))
            return pairs

        with tarfile.open(data_file) as tf:
            train_pairs = read_pairs(tf, "train")
            pairs = train_pairs if mode == "train" else read_pairs(tf,
                                                                   mode)
        src_dict = self._build_dict((p[0] for p in train_pairs),
                                    src_dict_size)
        trg_dict = self._build_dict((p[1] for p in train_pairs),
                                    trg_dict_size)
        self.src_ids, self.trg_ids = src_dict, trg_dict
        self._encode(pairs, src_dict, trg_dict)

    @classmethod
    def _build_dict(cls, seqs, size):
        import collections
        freq = collections.Counter()
        for s in seqs:
            freq.update(s)
        out = {cls._BOS: 0, cls._EOS: 1, cls._UNK: 2}
        for w, _ in sorted(freq.items(), key=lambda x: (-x[1], x[0])):
            if len(out) >= size:
                break
            if w not in out:
                out[w] = len(out)
        return out


__all__ += ["Imikolov", "Movielens", "Conll05st", "WMT14", "WMT16"]
