"""BERT / ERNIE model family — the flagship transformer configs.

The reference snapshot keeps BERT in the PaddleNLP companion repo built on
``paddle.nn.TransformerEncoder`` (python/paddle/nn/layer/transformer.py:607);
this module provides the same architecture natively so the framework's
headline benchmark (BERT-base pretraining, BASELINE.md config 3) is
self-contained.

TPU-native notes:
* One dense code path; tensor-parallel execution comes from tagging
  ``Parameter.sharding_axes`` (consumed by distributed.sharding_specs →
  pjit/GSPMD) via :func:`apply_megatron_sharding` — no parallel layer
  classes needed for the GSPMD path.
* Attention rides ``F.scaled_dot_product_attention`` (flash/Pallas path).
* Everything is static-shape; masks are additive f32 tensors computed from
  int token masks outside the hot loop.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.tensor import Tensor, to_tensor
from ...nn import functional as F
from ...nn.initializer import Normal
from ...nn.layer_base import Layer
from ...nn.layer_common import Dropout, Embedding, Linear
from ...nn.layer_norm_act import LayerNorm
from ...nn.layer_transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["BertModel", "BertForPretraining", "BertPretrainingCriterion",
           "BertForSequenceClassification", "ErnieModel",
           "ErnieForPretraining", "apply_megatron_sharding", "bert_base",
           "bert_large"]


class BertEmbeddings(Layer):
    """word + position + token_type embeddings → LayerNorm → dropout."""

    def __init__(self, vocab_size, hidden_size, hidden_dropout_prob,
                 max_position_embeddings, type_vocab_size,
                 initializer_range=0.02):
        super().__init__()
        init = Normal(std=initializer_range)
        from ...framework.param_attr import ParamAttr
        attr = ParamAttr(initializer=init)
        self.word_embeddings = Embedding(vocab_size, hidden_size,
                                         weight_attr=attr)
        self.position_embeddings = Embedding(max_position_embeddings,
                                             hidden_size, weight_attr=attr)
        self.token_type_embeddings = Embedding(type_vocab_size, hidden_size,
                                               weight_attr=attr)
        self.layer_norm = LayerNorm(hidden_size)
        self.dropout = Dropout(hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ...ops import manip_ops
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = manip_ops.arange(0, seq_len, 1, "int32")
            position_ids = manip_ops.unsqueeze(position_ids, 0)
        if token_type_ids is None:
            token_type_ids = manip_ops.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids) +
               self.position_embeddings(position_ids) +
               self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = Linear(hidden_size, hidden_size)

    def forward(self, hidden_states):
        from ...ops import manip_ops
        first = manip_ops.slice(hidden_states, [1], [0], [1])
        first = manip_ops.squeeze(first, [1])
        return F.tanh(self.dense(first))


class BertModel(Layer):
    """BERT encoder (paddlenlp-compatible constructor signature)."""

    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        super().__init__()
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.pad_token_id = pad_token_id
        self.initializer_range = initializer_range
        self.embeddings = BertEmbeddings(
            vocab_size, hidden_size, hidden_dropout_prob,
            max_position_embeddings, type_vocab_size, initializer_range)
        encoder_layer = TransformerEncoderLayer(
            hidden_size, num_attention_heads, intermediate_size,
            dropout=hidden_dropout_prob, activation=hidden_act,
            attn_dropout=attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = TransformerEncoder(encoder_layer, num_hidden_layers)
        self.pooler = BertPooler(hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        from ...autograd.engine import apply
        if attention_mask is None:
            import jax.numpy as jnp

            def make_mask(ids):
                # boolean keep-mask: exact semantics survive tracing, so
                # attention can prove it padding-shaped and stay on the
                # fused flash path (additive floats are opaque under jit)
                pad = jnp.asarray(self.pad_token_id, ids.dtype)
                return (ids != pad)[:, None, None, :]
            attention_mask = apply("bert_mask", make_mask, (input_ids,))
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        encoded = self.encoder(emb, attention_mask)
        pooled = self.pooler(encoded)
        return encoded, pooled


class BertLMPredictionHead(Layer):
    """MLM head: transform + LayerNorm + decoder tied to word embeddings."""

    def __init__(self, hidden_size, vocab_size, activation="gelu",
                 embedding_weights=None):
        super().__init__()
        self.transform = Linear(hidden_size, hidden_size)
        self.activation = getattr(F, activation)
        self.layer_norm = LayerNorm(hidden_size)
        # Tied decoder: reuse the word-embedding matrix [vocab, hidden].
        self.decoder_weight = embedding_weights
        self.decoder_bias = self.create_parameter(
            [vocab_size], is_bias=True)

    def forward(self, hidden_states, masked_positions=None):
        from ...ops import manip_ops, math_ops
        if masked_positions is not None:
            # gather the masked token positions: [B, S, H] → [B*M, H]
            b, s, h = hidden_states.shape
            flat = manip_ops.reshape(hidden_states, [b * s, h])
            hidden_states = manip_ops.gather(flat, masked_positions)
        x = self.layer_norm(self.activation(self.transform(hidden_states)))
        logits = math_ops.matmul(x, self.decoder_weight, transpose_y=True)
        return logits + self.decoder_bias


class BertForPretraining(Layer):
    """MLM + NSP pretraining heads over BertModel."""

    def __init__(self, bert: BertModel):
        super().__init__()
        self.bert = bert
        self.cls = BertLMPredictionHead(
            bert.hidden_size, bert.vocab_size,
            embedding_weights=bert.embeddings.word_embeddings.weight)
        self.seq_relationship = Linear(bert.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, masked_positions=None):
        encoded, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                    attention_mask)
        prediction_scores = self.cls(encoded, masked_positions)
        seq_relationship_score = self.seq_relationship(pooled)
        return prediction_scores, seq_relationship_score


class BertPretrainingCriterion(Layer):
    """MLM + NSP loss (softmax_with_cross_entropy over both heads)."""

    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels,
                masked_lm_scale=1.0):
        mlm = F.cross_entropy(prediction_scores, masked_lm_labels,
                              reduction="none", ignore_index=-1)
        from ...ops import math_ops
        mlm = math_ops.mean(math_ops.divide(
            mlm, to_tensor(float(masked_lm_scale))))
        nsp = F.cross_entropy(seq_relationship_score, next_sentence_labels,
                              reduction="mean")
        return mlm + nsp


class BertForSequenceClassification(Layer):
    def __init__(self, bert: BertModel, num_classes=2, dropout=None):
        super().__init__()
        self.bert = bert
        self.dropout = Dropout(dropout if dropout is not None else 0.1)
        self.classifier = Linear(bert.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


# ERNIE shares the BERT architecture at this scale (ERNIE 1.0/2.0/3.0-base
# differ in pretraining data/objectives, not the encoder).
ErnieModel = BertModel
ErnieForPretraining = BertForPretraining


def bert_base(**kw) -> BertModel:
    return BertModel(hidden_size=768, num_hidden_layers=12,
                     num_attention_heads=12, intermediate_size=3072, **kw)


def bert_large(**kw) -> BertModel:
    return BertModel(hidden_size=1024, num_hidden_layers=24,
                     num_attention_heads=16, intermediate_size=4096, **kw)


def ernie_1p5b(**kw) -> BertModel:
    """ERNIE-3.0 1.5B-class encoder (BASELINE config 4): hidden 2304,
    24 layers × (21.2M attn + 42.5M ffn) + 103M embeddings ≈ 1.63B params.
    The architecture is the shared BERT encoder (see ErnieModel note);
    this factory pins the 1.5B-scale hyperparameters the sharding bench
    trains with ZeRO-2 over the mesh."""
    kw.setdefault("vocab_size", 40000)
    kw.setdefault("max_position_embeddings", 2048)
    kw.setdefault("hidden_size", 2304)
    kw.setdefault("num_hidden_layers", 24)
    kw.setdefault("num_attention_heads", 18)
    kw.setdefault("intermediate_size", 9216)
    return ErnieModel(**kw)


def apply_megatron_sharding(model: Layer, mp_axis: str = "mp") -> Layer:
    """Tag transformer parameters with Megatron-style TP axes for GSPMD.

    Column-parallel (shard output dim): q/k/v projections, FFN up-proj.
    Row-parallel (shard input dim): attention out_proj, FFN down-proj.
    Vocab-parallel: embedding + tied MLM decoder shard the vocab dim.
    The reference expresses this with dedicated layer classes
    (fleet/meta_parallel/parallel_layers/mp_layers.py:29,85,143); under
    GSPMD the same partitioning is pure metadata on dense layers.
    """
    for name, p in model.named_parameters():
        axes = [None] * len(p.shape)
        if "word_embeddings" in name and len(p.shape) == 2:
            axes[0] = mp_axis
        elif any(k in name for k in ("q_proj", "k_proj", "v_proj",
                                     "linear1")):
            axes[-1] = mp_axis          # [in, out] → shard out
        elif any(k in name for k in ("out_proj", "linear2")):
            if len(p.shape) == 2:
                axes[0] = mp_axis       # [in, out] → shard in
        p.sharding_axes = tuple(axes) if any(a is not None
                                             for a in axes) else None
    return model
