"""Model zoo for paddle1_tpu.text (flagship transformer configs)."""

from .bert import (BertForPretraining, BertForSequenceClassification,
                   BertModel, BertPretrainingCriterion, ErnieForPretraining,
                   ErnieModel, apply_megatron_sharding, bert_base, bert_large,
                   ernie_1p5b)

__all__ = ["BertModel", "BertForPretraining", "BertPretrainingCriterion",
           "BertForSequenceClassification", "ErnieModel",
           "ErnieForPretraining", "apply_megatron_sharding", "bert_base",
           "bert_large", "ernie_1p5b"]
