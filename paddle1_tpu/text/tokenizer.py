"""Tokenization for the BERT/ERNIE family.

The reference keeps tokenizers in the PaddleNLP companion repo
(BasicTokenizer/WordpieceTokenizer/BertTokenizer); the framework needs them
in-tree for the pretraining configs to be runnable end-to-end. Pure-Python
host-side code (tokenization never belongs on the accelerator).
"""

from __future__ import annotations

import collections
import unicodedata
from typing import Dict, List, Optional

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "build_vocab"]


def _is_whitespace(ch):
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or \
            (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting + lowercasing."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        out = []
        buf = []
        for ch in text:
            if _is_control(ch):
                continue
            if _is_whitespace(ch):
                if buf:
                    out.append("".join(buf))
                    buf = []
                continue
            cp = ord(ch)
            if (0x4E00 <= cp <= 0x9FFF) or _is_punctuation(ch):
                if buf:
                    out.append("".join(buf))
                    buf = []
                out.append(ch)
                continue
            buf.append(ch)
        if buf:
            out.append("".join(buf))
        if self.do_lower_case:
            out = [unicodedata.normalize("NFD", t.lower()) for t in out]
            out = ["".join(c for c in t
                           if unicodedata.category(c) != "Mn")
                   for t in out]
        return [t for t in out if t]


class WordpieceTokenizer:
    """Greedy longest-match-first subword splitting."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_input_chars_per_word:
            return [self.unk_token]
        out = []
        start = 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out


class BertTokenizer:
    """vocab-file tokenizer with the paddlenlp surface: tokenize,
    convert_tokens_to_ids, __call__ producing input_ids/token_type_ids."""

    def __init__(self, vocab_file=None, vocab: Optional[Dict[str, int]]
                 = None, do_lower_case: bool = True, unk_token="[UNK]",
                 pad_token="[PAD]", cls_token="[CLS]", sep_token="[SEP]",
                 mask_token="[MASK]"):
        if vocab is None:
            if vocab_file is None:
                raise ValueError("need vocab_file or vocab dict")
            vocab = {}
            with open(vocab_file, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    vocab[line.rstrip("\n")] = i
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.unk_token = unk_token
        self.pad_token = pad_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.mask_token = mask_token

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> List[str]:
        out = []
        for tok in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(tok))
        return out

    def convert_tokens_to_ids(self, tokens) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids) -> List[str]:
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def __call__(self, text, text_pair=None, max_seq_len: int = 128,
                 pad_to_max_seq_len: bool = False):
        a = self.tokenize(text)
        b = self.tokenize(text_pair) if text_pair is not None else None
        # truncate to fit specials
        budget = max_seq_len - 2 - (1 if b is not None else 0)
        if b is not None:
            while len(a) + len(b) > budget:
                (a if len(a) >= len(b) else b).pop()
        else:
            a = a[:budget]
        tokens = [self.cls_token] + a + [self.sep_token]
        type_ids = [0] * len(tokens)
        if b is not None:
            tokens += b + [self.sep_token]
            type_ids += [1] * (len(b) + 1)
        ids = self.convert_tokens_to_ids(tokens)
        if pad_to_max_seq_len and len(ids) < max_seq_len:
            pad_id = self.vocab.get(self.pad_token, 0)
            pad = max_seq_len - len(ids)
            ids += [pad_id] * pad
            type_ids += [0] * pad
        return {"input_ids": ids, "token_type_ids": type_ids}


def build_vocab(texts, max_size: int = 30000, do_lower_case: bool = True,
                specials=("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")):
    """Frequency-sorted whole-word vocab from an iterable of texts (for
    hermetic tests / small corpora)."""
    basic = BasicTokenizer(do_lower_case)
    counter = collections.Counter()
    for t in texts:
        counter.update(basic.tokenize(t))
    vocab = {s: i for i, s in enumerate(specials)}
    for tok, _ in counter.most_common(max_size - len(specials)):
        if tok not in vocab:
            vocab[tok] = len(vocab)
    return vocab
