"""Online-learning embedding deltas: trainer → serving in seconds.

The reference closes its production loop by streaming trained sparse
rows from the trainer to the serving fleet without a redeploy (the
"online learning" half of the Paddle PS story). Here the transport is
a versioned, atomically-published file log — the same tmp-file +
``os.replace`` discipline the PR 2 checkpoint manifest uses, so a
reader never observes a half-written delta:

* :class:`DeltaLog` — the trainer side. ``publish(param, ids, rows)``
  writes ``delta-<version>.npz`` (ids + rows + target param name) and
  prunes old versions beyond ``keep``. Publishing is journaled with
  the PR 14 collective sanitizer (op ``delta_publish``) so a rank
  whose publish schedule diverges fails typed at verify.
* :class:`DeltaSubscriber` — the consumer side (a serving replica or
  an in-process test). A polling daemon applies every new version in
  order through ``apply_fn(param, ids, rows)`` — for serving, that is
  ``InferenceEngine.update_param_rows``, which rewrites rows of a
  jit-ARGUMENT param dict: same shapes/dtypes, so a delta never
  recompiles anything. ``wait_version`` is the test/latency hook.

Versions are a monotone integer. The log directory is the unit of
deployment: point the fleet's ``delta_dir`` at the trainer's log and
click feedback is servable in < poll interval + one dispatch.
"""

from __future__ import annotations

import glob
import logging
import os
import re
import tempfile
import threading
import time
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from ..core import collective_sanitizer as _csan
from ..core.errors import InvalidArgumentError

__all__ = ["DeltaRecord", "DeltaLog", "DeltaSubscriber", "read_since"]

_log = logging.getLogger("paddle1_tpu.embedding_delta")

_NAME_RE = re.compile(r"delta-(\d{12})\.npz$")


class DeltaRecord(NamedTuple):
    version: int
    param: str
    ids: np.ndarray    # int64 [n]
    rows: np.ndarray   # float32 [n, dim]


def _version_of(path: str) -> Optional[int]:
    m = _NAME_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def read_since(directory: str, version: int) -> List[DeltaRecord]:
    """Every record in ``directory`` with version > ``version``, in
    order. A file pruned from under a lagging reader is skipped (the
    reader should then resync from a checkpoint — deltas are a cache,
    the manifest checkpoint is the source of truth)."""
    out = []
    for p in sorted(glob.glob(os.path.join(directory, "delta-*.npz"))):
        v = _version_of(p)
        if v is None or v <= version:
            continue
        try:
            with np.load(p, allow_pickle=False) as z:
                out.append(DeltaRecord(
                    int(z["version"]), str(z["param"]),
                    np.asarray(z["ids"], np.int64),
                    np.asarray(z["rows"], np.float32)))
        except (OSError, ValueError, KeyError):
            continue   # pruned/half-visible on exotic fs: next poll
    return out


class DeltaLog:
    """Versioned npz delta stream over one directory (trainer side)."""

    def __init__(self, directory: str, keep: int = 64):
        self.directory = str(directory)
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError("keep must be >= 1")
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._version = self.latest_version()

    # -- write side ---------------------------------------------------------

    def publish(self, param: str, ids, rows,
                version: Optional[int] = None) -> int:
        """Atomically publish one delta; returns its version. Rows must
        be [n, dim] aligned with ids [n]."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[0] != ids.shape[0]:
            raise InvalidArgumentError(
                f"delta rows must be [len(ids), dim]; got ids "
                f"{ids.shape} rows {rows.shape}")
        _csan.note_collective("delta_publish", (ids, rows),
                              site="DeltaLog.publish")
        with self._lock:
            v = self._version + 1 if version is None else int(version)
            if v <= self._version:
                raise InvalidArgumentError(
                    f"delta version {v} is not past the log head "
                    f"{self._version} — versions are monotone")
            final = os.path.join(self.directory, f"delta-{v:012d}.npz")
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez(f, version=np.int64(v),
                             param=np.asarray(param),
                             ids=ids, rows=rows)
                os.replace(tmp, final)   # readers see all or nothing
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self._version = v
            self._prune_locked()
            return v

    def _prune_locked(self) -> None:
        files = sorted(p for p in glob.glob(
            os.path.join(self.directory, "delta-*.npz"))
            if _version_of(p) is not None)
        for p in files[:-self.keep]:
            try:
                os.unlink(p)
            except OSError:
                pass

    # -- read side ----------------------------------------------------------

    def latest_version(self) -> int:
        vs = [_version_of(p) for p in glob.glob(
            os.path.join(self.directory, "delta-*.npz"))]
        vs = [v for v in vs if v is not None]
        return max(vs) if vs else 0

    def read_since(self, version: int) -> List[DeltaRecord]:
        return read_since(self.directory, version)


class DeltaSubscriber:
    """Polling consumer: applies new delta versions in order through
    ``apply_fn(param, ids, rows)``. Daemon thread; exactly-once per
    version (monotone ``applied_version``)."""

    def __init__(self, directory: str, apply_fn: Callable,
                 poll_s: float = 0.05, metrics=None,
                 from_version: int = 0):
        self.directory = str(directory)
        self._apply = apply_fn
        self.poll_s = float(poll_s)
        self.metrics = metrics
        self.applied_version = int(from_version)
        self._stop = threading.Event()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DeltaSubscriber":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="embedding-delta-sub")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def poll_once(self) -> int:
        """Apply everything new right now (synchronous test surface);
        returns how many records were applied."""
        recs = read_since(self.directory, self.applied_version)
        n = 0
        for r in recs:
            try:
                self._apply(r.param, r.ids, r.rows)
            except Exception as e:  # noqa: broad-except — one bad
                # delta (renamed param, stale dim) must not kill the
                # consumer; it is logged, counted, and skipped
                _log.warning("delta v%d apply failed: %s", r.version, e)
                if self.metrics is not None:
                    self.metrics.counter(
                        "embed_delta_errors_total").inc()
            else:
                n += 1
                if self.metrics is not None:
                    self.metrics.counter(
                        "embed_delta_applied_total").inc()
                    self.metrics.counter(
                        "embed_delta_rows_total").inc(
                            int(r.ids.shape[0]))
            with self._cond:
                self.applied_version = r.version
                self._cond.notify_all()
        if self.metrics is not None and recs:
            self.metrics.gauge("embed_delta_version").set(
                self.applied_version)
        return n

    def wait_version(self, version: int,
                     timeout: Optional[float] = None) -> bool:
        """Block until ``applied_version >= version`` (latency probe)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while self.applied_version < version:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=left if left is not None
                              else 1.0)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: broad-except — a transient
                # fs error must not end the subscription
                _log.warning("delta poll failed: %s", e)
            self._stop.wait(self.poll_s)
