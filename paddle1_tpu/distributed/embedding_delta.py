"""Online-learning embedding deltas: trainer → serving in seconds.

The reference closes its production loop by streaming trained sparse
rows from the trainer to the serving fleet without a redeploy (the
"online learning" half of the Paddle PS story). Here the transport is
a versioned, atomically-published file log — the same tmp-file +
``os.replace`` discipline the PR 2 checkpoint manifest uses, so a
reader never observes a half-written delta:

* :class:`DeltaLog` — the trainer side. ``publish(param, ids, rows)``
  writes ``delta-<version>.npz`` (ids + rows + target param name + a
  CRC over the payload) and prunes old versions beyond ``keep``.
  ``publish_snapshot`` writes a full-row ``snap-<version>.npz`` anchor
  (typically at the trainer's checkpoint barrier) — the resync source
  for a reader that fell off the pruned tail. Publishing is journaled
  with the PR 14 collective sanitizer (op ``delta_publish``) so a rank
  whose publish schedule diverges fails typed at verify.
* :class:`DeltaSubscriber` — the consumer side (a serving replica or
  an in-process test). A polling daemon applies every new version in
  order through ``apply_fn(param, ids, rows)`` — for serving, that is
  ``InferenceEngine.update_param_rows``, which rewrites rows of a
  jit-ARGUMENT param dict: same shapes/dtypes, so a delta never
  recompiles anything. ``wait_version`` is the test/latency hook.

Exactly-once discipline (ISSUE 20): every record carries a CRC that is
verified before apply — a torn or bit-flipped file is *skipped and
counted* (``delta_skipped_files_total`` / ``delta_corrupt_total``),
never applied. A version GAP (a file pruned or corrupted from under a
lagging reader) is no longer silently jumped: the subscriber counts it
(``delta_gaps_total``), resyncs from the newest snapshot or a caller
``resync_fn`` (``delta_resyncs_total``), and if neither covers the gap
raises the typed :class:`DeltaGapDetected` and STALLS — knowingly
stale, with the ``embed_delta_staleness_seconds`` gauge growing so a
``stale(embed_delta_staleness_seconds)<N`` SLO clause
(``FLAGS_obs_slos``) turns it into a ``/healthz`` verdict — instead of
serving stale rows forever.

Versions are a monotone integer. The log directory is the unit of
deployment: point the fleet's ``delta_dir`` at the trainer's log and
click feedback is servable in < poll interval + one dispatch.
"""

from __future__ import annotations

import glob
import logging
import os
import re
import tempfile
import threading
import time
import zipfile
import zlib
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from ..core import chaos as _chaos
from ..core import collective_sanitizer as _csan
from ..core.errors import InvalidArgumentError, UnavailableError

__all__ = ["DeltaRecord", "DeltaLog", "DeltaSubscriber",
           "DeltaGapDetected", "read_since", "latest_snapshot"]

_log = logging.getLogger("paddle1_tpu.embedding_delta")

_NAME_RE = re.compile(r"delta-(\d{12})\.npz$")
_SNAP_RE = re.compile(r"snap-(\d{12})\.npz$")

# directories we already warned about skipped files for (satellite:
# warn once per directory, count every skip)
_skip_warned: set = set()
_skip_lock = threading.Lock()


class DeltaGapDetected(UnavailableError):
    """The delta stream has a version hole this reader cannot bridge:
    files between its applied version and the oldest available version
    were pruned or corrupted, and no snapshot (or ``resync_fn``) covers
    the range. The replica is knowingly stale — resync it from a
    checkpoint (have the trainer ``publish_snapshot``) instead of
    letting it serve old rows forever."""


class DeltaRecord(NamedTuple):
    version: int
    param: str
    ids: np.ndarray    # int64 [n]
    rows: np.ndarray   # float32 [n, dim]
    crc: int = 0       # zlib.crc32 over param/ids/rows (0 = legacy file)


def _crc(param: str, ids: np.ndarray, rows: np.ndarray) -> int:
    c = zlib.crc32(str(param).encode())
    c = zlib.crc32(np.ascontiguousarray(ids).tobytes(), c)
    return zlib.crc32(np.ascontiguousarray(rows).tobytes(), c)


def _version_of(path: str) -> Optional[int]:
    m = _NAME_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _snap_version_of(path: str) -> Optional[int]:
    m = _SNAP_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _load_record(path: str) -> DeltaRecord:
    """Load + CRC-verify one delta/snapshot file (raises ValueError on
    a checksum mismatch so callers treat corruption like a torn file)."""
    with np.load(path, allow_pickle=False) as z:
        rec = DeltaRecord(
            int(z["version"]), str(z["param"]),
            np.asarray(z["ids"], np.int64),
            np.asarray(z["rows"], np.float32),
            int(z["crc"]) if "crc" in z else 0)
    if rec.crc and rec.crc != _crc(rec.param, rec.ids, rec.rows):
        raise ValueError(f"crc mismatch in {os.path.basename(path)}")
    return rec


def _count_skip(directory: str, path: str, err: Exception,
                metrics=None, corrupt: bool = False) -> None:
    """Count (and warn once per directory about) a skipped file."""
    if metrics is None:
        from ..obs.registry import process_registry
        metrics = process_registry()
    metrics.counter("delta_skipped_files_total").inc()
    if corrupt:
        metrics.counter("delta_corrupt_total").inc()
    with _skip_lock:
        first = directory not in _skip_warned
        if first:
            _skip_warned.add(directory)
    if first:
        _log.warning(
            "skipping unreadable delta file %s (%s) — pruned from under "
            "this reader or corrupt; counted in "
            "delta_skipped_files_total (warned once per directory)",
            path, err)


def read_since(directory: str, version: int,
               metrics=None) -> List[DeltaRecord]:
    """Every record in ``directory`` with version > ``version``, in
    order. A file pruned from under a lagging reader — or one whose CRC
    no longer matches its payload — is skipped, counted
    (``delta_skipped_files_total``; corruption additionally in
    ``delta_corrupt_total``) and warned about once per directory. The
    reader should then resync from a checkpoint: deltas are a cache,
    the manifest checkpoint is the source of truth."""
    out = []
    for p in sorted(glob.glob(os.path.join(directory, "delta-*.npz"))):
        v = _version_of(p)
        if v is None or v <= version:
            continue
        try:
            out.append(_load_record(p))
        except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
            # pruned/half-visible on exotic fs, or corrupt (our CRC or
            # the zip container's): skip + count, next poll/resync
            corrupt = (isinstance(e, zipfile.BadZipFile)
                       or "crc mismatch" in str(e))
            _count_skip(directory, p, e, metrics, corrupt=corrupt)
    return out


def latest_snapshot(directory: str, metrics=None) -> Optional[DeltaRecord]:
    """The newest readable full-row snapshot in ``directory`` (None if
    there is none). Unreadable snapshots are counted like skipped
    deltas and the next-newest is tried."""
    paths = sorted((p for p in glob.glob(
        os.path.join(directory, "snap-*.npz"))
        if _snap_version_of(p) is not None), reverse=True)
    for p in paths:
        try:
            return _load_record(p)
        except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
            corrupt = (isinstance(e, zipfile.BadZipFile)
                       or "crc mismatch" in str(e))
            _count_skip(directory, p, e, metrics, corrupt=corrupt)
    return None


class DeltaLog:
    """Versioned npz delta stream over one directory (trainer side)."""

    def __init__(self, directory: str, keep: int = 64):
        self.directory = str(directory)
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError("keep must be >= 1")
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._version = self.latest_version()

    # -- write side ---------------------------------------------------------

    def _write_versioned(self, prefix: str, v: int, param: str,
                         ids: np.ndarray, rows: np.ndarray) -> str:
        """tmp-write + fsync + atomic rename of one versioned npz (the
        commit discipline the module docstring promises): readers see
        the whole file with a valid CRC, or no file at all."""
        final = os.path.join(self.directory, f"{prefix}-{v:012d}.npz")
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, version=np.int64(v),
                         param=np.asarray(param), ids=ids, rows=rows,
                         crc=np.int64(_crc(param, ids, rows)))
                f.flush()
                os.fsync(f.fileno())
            if prefix == "delta" and _chaos.check_delta_corrupt():
                # chaos `delta_corrupt`: bit-flip the committed payload
                # AFTER the CRC was computed — the reader's verify must
                # catch it (skip + count), never apply it
                with open(tmp, "r+b") as f:
                    f.seek(max(0, os.path.getsize(tmp) // 2))
                    f.write(b"\xde\xad\xbe\xef")
            os.replace(tmp, final)   # readers see all or nothing
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return final

    def publish(self, param: str, ids, rows,
                version: Optional[int] = None) -> int:
        """Atomically publish one delta; returns its version. Rows must
        be [n, dim] aligned with ids [n]."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[0] != ids.shape[0]:
            raise InvalidArgumentError(
                f"delta rows must be [len(ids), dim]; got ids "
                f"{ids.shape} rows {rows.shape}")
        _csan.note_collective("delta_publish", (ids, rows),
                              site="DeltaLog.publish")
        with self._lock:
            v = self._version + 1 if version is None else int(version)
            if v <= self._version:
                raise InvalidArgumentError(
                    f"delta version {v} is not past the log head "
                    f"{self._version} — versions are monotone")
            self._write_versioned("delta", v, param, ids, rows)
            self._version = v
            if _chaos.check_delta_gap():
                # chaos `delta_gap`: prune everything but the head from
                # under any lagging reader — the subscriber must detect
                # the hole typed, not silently jump it
                self._prune_locked(keep=1)
            else:
                self._prune_locked()
            return v

    def publish_snapshot(self, param: str, ids, rows) -> int:
        """Atomically publish a FULL-ROW snapshot anchor (every trained
        row of ``param``) at the next version. Published at the
        trainer's checkpoint barrier, it is what a gapped subscriber
        resyncs from; older snapshots are pruned (the new anchor
        supersedes them). Deltas are deliberately LEFT to the ``keep``
        window: a reader lagging a few versions behind the anchor keeps
        its contiguous stream instead of being forced through a resync
        on every snapshot."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[0] != ids.shape[0]:
            raise InvalidArgumentError(
                f"snapshot rows must be [len(ids), dim]; got ids "
                f"{ids.shape} rows {rows.shape}")
        with self._lock:
            v = self._version + 1
            self._write_versioned("snap", v, param, ids, rows)
            self._version = v
            # one snapshot is the resync anchor; the previous ones can
            # go. Deltas stay under the keep-window so an in-stream
            # reader is not gapped by its own anchor.
            snaps = sorted(p for p in glob.glob(
                os.path.join(self.directory, "snap-*.npz"))
                if _snap_version_of(p) is not None)
            for p in snaps[:-1]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            self._prune_locked()
            return v

    def _prune_locked(self, keep: Optional[int] = None) -> None:
        keep = self.keep if keep is None else keep
        files = sorted(p for p in glob.glob(
            os.path.join(self.directory, "delta-*.npz"))
            if _version_of(p) is not None)
        for p in files[:-keep]:
            try:
                os.unlink(p)
            except OSError:
                pass

    # -- read side ----------------------------------------------------------

    def latest_version(self) -> int:
        vs = [_version_of(p) for p in glob.glob(
            os.path.join(self.directory, "delta-*.npz"))]
        vs += [_snap_version_of(p) for p in glob.glob(
            os.path.join(self.directory, "snap-*.npz"))]
        vs = [v for v in vs if v is not None]
        return max(vs) if vs else 0

    def read_since(self, version: int) -> List[DeltaRecord]:
        return read_since(self.directory, version)


class DeltaSubscriber:
    """Polling consumer: applies new delta versions in order through
    ``apply_fn(param, ids, rows)``. Daemon thread; exactly-once per
    version (monotone ``applied_version``), CRC-verified reads, typed
    gap detection with snapshot/``resync_fn`` recovery (see module
    docstring)."""

    def __init__(self, directory: str, apply_fn: Callable,
                 poll_s: float = 0.05, metrics=None,
                 from_version: int = 0,
                 resync_fn: Optional[Callable[[], int]] = None):
        self.directory = str(directory)
        self._apply = apply_fn
        self.poll_s = float(poll_s)
        self.metrics = metrics
        self.applied_version = int(from_version)
        # resync_fn() restores this reader's full state from an
        # external checkpoint and returns the delta version that state
        # corresponds to (preferred over the in-log snapshot when set)
        self._resync = resync_fn
        self._stop = threading.Event()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._in_gap = False        # gap counted once per episode
        self._gap_warned = False    # daemon warns once per episode
        self._stale_since: Optional[float] = None

    def start(self) -> "DeltaSubscriber":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="embedding-delta-sub")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- gap recovery -------------------------------------------------------

    def _counter(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc()
        from ..obs.registry import process_registry
        if self.metrics is not process_registry():
            process_registry().counter(name).inc()

    def _set_applied(self, version: int) -> None:
        with self._cond:
            self.applied_version = version
            self._cond.notify_all()

    def _recover_gap(self, first_avail: int) -> None:
        """Bridge ``applied_version + 1 .. first_avail - 1``. Counts the
        gap once, then tries ``resync_fn`` / the newest snapshot; if
        neither covers the hole, raises :class:`DeltaGapDetected` (the
        caller stays knowingly stale and retries next poll — a later
        ``publish_snapshot`` heals it)."""
        if not self._in_gap:
            self._in_gap = True
            self._counter("delta_gaps_total")
        if self._resync is not None:
            v = int(self._resync())
            self._counter("delta_resyncs_total")
            self._set_applied(max(v, self.applied_version))
            self._in_gap = self._gap_warned = False
            return
        snap = latest_snapshot(self.directory, self.metrics)
        if snap is not None and snap.version > self.applied_version \
                and snap.version + 1 >= first_avail:
            self._apply(snap.param, snap.ids, snap.rows)
            self._counter("delta_resyncs_total")
            self._set_applied(snap.version)
            self._in_gap = self._gap_warned = False
            return
        raise DeltaGapDetected(
            f"delta log {self.directory} has a version hole: applied "
            f"{self.applied_version}, oldest available {first_avail}, "
            f"and no snapshot/resync_fn covers the gap — the replica "
            f"is stale until the trainer publishes a snapshot "
            f"(DeltaLog.publish_snapshot) or a resync_fn is wired")

    def _publish_staleness(self) -> None:
        """Seconds this reader has been behind the log head (0 when
        caught up) — the gauge a ``stale(...)`` SLO clause watches."""
        vs = [_version_of(p) for p in glob.glob(
            os.path.join(self.directory, "delta-*.npz"))]
        vs += [_snap_version_of(p) for p in glob.glob(
            os.path.join(self.directory, "snap-*.npz"))]
        head = max((v for v in vs if v is not None), default=0)
        now = time.monotonic()
        if head > self.applied_version:
            if self._stale_since is None:
                self._stale_since = now
            stale = now - self._stale_since
        else:
            self._stale_since = None
            stale = 0.0
        if self.metrics is not None:
            self.metrics.gauge("embed_delta_staleness_seconds").set(stale)
        from ..obs.registry import process_registry
        if self.metrics is not process_registry():
            process_registry().gauge(
                "embed_delta_staleness_seconds").set(stale)

    def poll_once(self) -> int:
        """Apply everything new right now (synchronous test surface);
        returns how many records were applied. Raises
        :class:`DeltaGapDetected` when the stream has an uncoverable
        hole (see :meth:`_recover_gap`)."""
        try:
            recs = read_since(self.directory, self.applied_version,
                              self.metrics)
            n = 0
            shead = max((v for v in (
                _snap_version_of(p) for p in glob.glob(
                    os.path.join(self.directory, "snap-*.npz")))
                if v is not None), default=0)
            if shead == self.applied_version + 1:
                # the anchor IS the next version in the stream — the
                # trainer's routine snapshot publish, not a hole: apply
                # it like any record and keep streaming (no gap episode)
                snap = latest_snapshot(self.directory, self.metrics)
                if snap is not None and snap.version == shead:
                    try:
                        self._apply(snap.param, snap.ids, snap.rows)
                    except Exception as e:  # noqa: broad-except — one
                        # bad snapshot must not kill the consumer; it
                        # is logged, counted, and skipped like a delta
                        _log.warning("snapshot v%d apply failed: %s",
                                     snap.version, e)
                        if self.metrics is not None:
                            self.metrics.counter(
                                "embed_delta_errors_total").inc()
                    else:
                        n += 1
                        if self.metrics is not None:
                            self.metrics.counter(
                                "embed_delta_applied_total").inc()
                            self.metrics.counter(
                                "embed_delta_rows_total").inc(
                                    int(snap.ids.shape[0]))
                    self._set_applied(shead)
                    recs = [r for r in recs if r.version > shead]
            first_avail = recs[0].version if recs else None
            if first_avail is None and shead > self.applied_version:
                # nothing readable past us: a snapshot AHEAD of us means
                # the deltas we needed were pruned/superseded — that is
                # a gap too, not "caught up"
                first_avail = shead + 1
            if first_avail is not None \
                    and first_avail > self.applied_version + 1:
                self._recover_gap(first_avail)
                recs = read_since(self.directory, self.applied_version,
                                  self.metrics)
                if recs and recs[0].version > self.applied_version + 1:
                    # the resync anchor predates the hole: still stale
                    raise DeltaGapDetected(
                        f"resync landed at {self.applied_version} but "
                        f"the oldest available delta is "
                        f"{recs[0].version} — the gap persists")
            for r in recs:
                try:
                    self._apply(r.param, r.ids, r.rows)
                except Exception as e:  # noqa: broad-except — one bad
                    # delta (renamed param, stale dim) must not kill the
                    # consumer; it is logged, counted, and skipped
                    _log.warning("delta v%d apply failed: %s",
                                 r.version, e)
                    if self.metrics is not None:
                        self.metrics.counter(
                            "embed_delta_errors_total").inc()
                else:
                    n += 1
                    if self.metrics is not None:
                        self.metrics.counter(
                            "embed_delta_applied_total").inc()
                        self.metrics.counter(
                            "embed_delta_rows_total").inc(
                                int(r.ids.shape[0]))
                self._set_applied(r.version)
            if n:
                self._in_gap = self._gap_warned = False
            if self.metrics is not None and recs:
                self.metrics.gauge("embed_delta_version").set(
                    self.applied_version)
            return n
        finally:
            self._publish_staleness()

    def wait_version(self, version: int,
                     timeout: Optional[float] = None) -> bool:
        """Block until ``applied_version >= version`` (latency probe)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while self.applied_version < version:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=left if left is not None
                              else 1.0)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except DeltaGapDetected as e:
                # knowingly stale: stay subscribed (a later snapshot
                # heals the gap), warn once per episode, let the
                # staleness gauge carry the alarm
                if not self._gap_warned:
                    self._gap_warned = True
                    _log.warning("delta stream stalled on gap: %s", e)
            except Exception as e:  # noqa: broad-except — a transient
                # fs error must not end the subscription
                _log.warning("delta poll failed: %s", e)
            self._stop.wait(self.poll_s)
