"""Fault-tolerant training runtime around :class:`ParallelEngine`.

Reference analog: ``incubate/auto_checkpoint`` (trainer-side periodic
checkpoint + resume-from-epoch on restart) and the dynamic-loss-scaling
"skip bad step" protocol of ``update_loss_scaling_op`` — composed here
into one loop so a long run survives the three killers of multi-host
training: bad batches (NaN/Inf), transient step/save failures, and
preemptions.

Division of labor:

* the **device** detects and neutralizes bad steps — the engine's
  ``check_finite`` step computes an isfinite flag over loss+grads inside
  the compiled executable and where-selects the old params when it
  trips, so a poisoned batch can never corrupt the model even while the
  host dispatches ahead; the flag rides the loss's packed readback
  (:class:`~paddle1_tpu.core.async_loss.StepFuture`) at zero extra cost;
* the **host** decides what a bad step *means* — policy ``raise`` /
  ``skip`` / ``restore_last_good`` — feeds the outcome into
  :class:`~paddle1_tpu.amp.GradScaler` dynamic scaling when one is
  attached, watches for loss explosions (finite but diverging), retries
  transient failures with bounded exponential backoff, checkpoints
  every ``save_freq`` steps through the atomic-commit
  :class:`CheckpointManager`, and resumes — params, optimizer state,
  RNG stream, LR schedule, and data-iterator position — from the newest
  checkpoint that verifies.

Determinism contract: ``fit`` consumes exactly one batch of the
(replayable) ``data`` stream per global step and one RNG key per step,
and checkpoints carry the RNG/LR state — so a run that is preempted,
restored and replayed is bit-compatible with an uninterrupted run. The
chaos tests (tests/test_resilience.py) assert this to 1e-6.

Usage::

    engine = ParallelEngine(model, opt, loss_fn, check_finite=True)
    trainer = ResilientTrainer(engine, "/ckpts/run7", save_freq=100,
                               bad_step_policy="skip")
    report = trainer.fit(lambda: loader, steps=10_000)
    # kill -9 at any point; rerunning the same script resumes from the
    # last committed checkpoint and reports report.resumed_from
"""

from __future__ import annotations

import itertools
import time
import warnings

import numpy as np
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Optional

from ..core import chaos
from ..core import flags as core_flags
from ..core import health
from ..core.errors import InvalidArgumentError
from ..core.generator import get_rng_state, set_rng_state
from ..obs import events as obs_events
from ..obs import registry as obs_registry
from .checkpoint import CheckpointCorruptError, CheckpointManager
from .ps import pack_table_state, unpack_table_state

__all__ = ["ResilientTrainer", "ResilienceReport", "BadStepError"]

POLICIES = ("raise", "skip", "restore_last_good")


class BadStepError(FloatingPointError):
    """A non-finite (or diverged) training step under policy 'raise'.
    The model params are still at their last good values: the compiled
    step skipped the poisoned update on device before the host saw the
    flag."""


@dataclass
class ResilienceReport:
    """What the resilient loop actually did (the counters the chaos
    acceptance matrix checks)."""
    steps_done: int = 0            # unique applied steps (net progress)
    steps_replayed: int = 0        # applied again after a rollback
    bad_steps: int = 0             # non-finite flags seen
    steps_skipped: int = 0         # bad steps consumed under 'skip'
    divergence_trips: int = 0      # finite-but-exploding losses
    retries: int = 0               # transient-failure retries (step+save)
    restores: int = 0              # checkpoint rollbacks (any cause)
    preemptions: int = 0           # preemption signals handled
    checkpoints_written: int = 0
    checkpoint_write_failures: int = 0  # saves abandoned after retries
    resumed_from: Optional[int] = None  # step picked up on fit() entry
    final_step: int = 0
    final_loss: Optional[float] = None
    # input-pipeline counters (aggregated over every DataLoader the data
    # factory handed this fit — the loader-side half of the fault matrix)
    bad_samples: int = 0           # sample fetches dropped (skip+quarantine)
    samples_quarantined: int = 0   # of those, logged under 'quarantine'
    loader_worker_restarts: int = 0  # dead/stalled worker re-spawns
    loader_stalls: int = 0         # input-stall watchdog trips
    # how the data stream was repositioned after restore/resume:
    # 'state' = O(1) checkpointable-loader restore, 'replay' = legacy
    # O(steps) fast-forward, None = never repositioned
    loader_resume: Optional[str] = None
    loader_state_restores: int = 0  # O(1) restores performed
    # restores whose checkpoint was written on a DIFFERENT mesh (an
    # elastic world-resize): params/opt state arrived via the
    # manifest-driven shard remap, not a same-layout load
    resharded_restores: int = 0

    def as_dict(self) -> Dict[str, Any]:
        from dataclasses import asdict
        return asdict(self)


def _flag_default(value, name):
    return core_flags.flag(name) if value is None else value


def _is_dataloader(obj) -> bool:
    # lazy: resilience must stay importable without dragging the io
    # package (and its device probing) into every distributed import
    from ..io.dataloader import DataLoader
    return isinstance(obj, DataLoader)


class ResilientTrainer:
    """Periodic-checkpoint, resume, retry and bad-step-policy wrapper
    around a ``check_finite`` :class:`ParallelEngine`.

    Parameters
    ----------
    engine : ParallelEngine built with ``check_finite=True`` (the
        device-side detection the policies depend on).
    directory : checkpoint directory (a ``CheckpointManager`` over it).
    save_freq : checkpoint every N applied steps (flag ``ft_save_freq``).
    bad_step_policy : 'raise' | 'skip' | 'restore_last_good'
        (flag ``ft_bad_step_policy``). ``skip`` counts the step and
        moves on (the update was already skipped on device);
        ``restore_last_good`` rolls back to the newest verified
        checkpoint and replays the data stream from there (a poisoned
        occurrence is injected/transient, so the replay comes back
        clean). A *finite* loss caught by the divergence watchdog
        cannot be skipped post-hoc (its update was applied), so under
        both non-raise policies it restores.
    max_retries / backoff_base_s / backoff_max_s : bounded exponential
        backoff around transient step/save failures (``ft_*`` flags).
        Only ``Exception`` is retried: ``KeyboardInterrupt``,
        ``SystemExit`` and :class:`SimulatedPreemption` always unwind.
    divergence_factor : loss > factor * running-mean ⇒ bad step
        (0 disables; flag ``ft_divergence_factor``).
    scaler : optional :class:`~paddle1_tpu.amp.GradScaler`; every step
        outcome is fed to ``scaler.record_step`` so dynamic loss
        scaling tracks device-detected overflows.
    max_to_keep : checkpoint retention window.

    Performance note: per-step policy decisions (and the watchdog)
    require reading the packed loss+flag back every step, which costs
    one host round trip per step — the robustness tax. Params can
    never go bad regardless (the where-select skip happens on device),
    so throughput-critical runs should keep using
    ``engine.step_stream``/``step_many`` (flags still computed, read
    per chunk) and reserve ResilientTrainer for runs where per-step
    policy reaction and auto-restore matter; a lagged-flag mode
    (react within ``inflight_window`` steps) is the natural extension.
    """

    def __init__(self, engine, directory: str,
                 save_freq: Optional[int] = None,
                 bad_step_policy: Optional[str] = None,
                 max_retries: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 divergence_factor: Optional[float] = None,
                 scaler=None, max_to_keep: int = 3,
                 sleep: Callable[[float], None] = time.sleep):
        if not getattr(engine, "check_finite", False):
            raise InvalidArgumentError(
                "ResilientTrainer needs an engine built with "
                "check_finite=True — bad-step policies are driven by "
                "the device-side isfinite flag")
        self.engine = engine
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)
        self.save_freq = int(_flag_default(save_freq, "ft_save_freq"))
        self.policy = _flag_default(bad_step_policy, "ft_bad_step_policy")
        if self.policy not in POLICIES:
            raise InvalidArgumentError(
                f"bad_step_policy must be one of {POLICIES}, "
                f"got {self.policy!r}")
        self.max_retries = int(_flag_default(max_retries, "ft_max_retries"))
        self.backoff_base_s = float(
            _flag_default(backoff_base_s, "ft_backoff_base_s"))
        self.backoff_max_s = float(
            _flag_default(backoff_max_s, "ft_backoff_max_s"))
        self.divergence_factor = float(
            _flag_default(divergence_factor, "ft_divergence_factor"))
        self.scaler = scaler
        self._sleep = sleep
        self.report = ResilienceReport()
        self._loss_ema: Optional[float] = None
        self._ema_warmup = 0
        self._restore_streak = (None, 0)  # (global step, repeats)
        self._last_saved: Optional[int] = None
        self._active_loader = None        # checkpointable DataLoader in use
        self._seen_loaders: list = []     # every loader this fit touched
        self._restored_loader_state = None  # meta['loader'] of last restore
        self._replay_warned = False
        self._embed_engine = None     # ShardedEmbeddingEngine, if attached
        self._embed_comm = None       # SparseAsyncCommunicator, if attached
        chaos.configure_from_flags()  # no-op when FLAGS_ft_chaos empty

    # -- engine state <-> checkpoint ------------------------------------

    def _state(self):
        return {"params": self.engine.params,
                "opt_state": self.engine.opt_state}

    def attach_embedding(self, engine, communicator=None) -> None:
        """Register the sharded embedding stack (and optionally its
        async communicator) so its state rides every checkpoint as an
        ``embed`` sidecar: the engine's admission ledger / LFU / TTL
        bookkeeping and per-row adam step counts, the host/remote tier
        rows+slots, and the communicator's push/apply counters. The
        communicator is quiesced (``state_dict`` flushes) inside the
        existing save barrier, so an evict/re-admit round trip after a
        crash replays bit-identically to the uninterrupted run."""
        self._embed_engine = engine
        self._embed_comm = communicator

    def _embed_sidecar(self):
        """(arrays, meta-summary) for the ``embed`` sidecar, or None."""
        if self._embed_engine is None:
            return None
        eng = self._embed_engine
        arrays = {}
        for k, v in eng.state_dict().items():
            arrays[f"engine/{k}"] = v
        if self._embed_comm is not None:
            comm_state = self._embed_comm.state_dict()  # flush = quiesce
            host_state = comm_state["service"]
            arrays["comm/counters"] = np.asarray(
                [comm_state["pushed_total"], comm_state["applied_total"]],
                np.int64)
        else:
            host_state = eng.host.state_dict()
        shard_states = (host_state["shards"]
                        if "shards" in host_state else [host_state])
        arrays["host/num_shards"] = np.asarray(len(shard_states), np.int64)
        host_rows = 0
        for k, sd in enumerate(shard_states):
            packed = pack_table_state(sd)
            host_rows += int(packed["ids"].shape[0])
            for name, arr in packed.items():
                arrays[f"host/shard{k}/{name}"] = arr
        summary = {"resident": int(arrays["engine/ids"].shape[0]),
                   "host_rows": host_rows,
                   "num_shards": len(shard_states)}
        if "comm/counters" in arrays:
            summary["pushed_total"] = int(arrays["comm/counters"][0])
            summary["applied_total"] = int(arrays["comm/counters"][1])
        return arrays, summary

    def _restore_embed(self, ckpt_step: int, meta: Dict[str, Any]) -> None:
        if self._embed_engine is None or "embed" not in meta:
            return
        arrays = self.manager.read_sidecar("embed", ckpt_step)
        eng_state = {k.split("/", 1)[1]: v for k, v in arrays.items()
                     if k.startswith("engine/")}
        n = int(arrays["host/num_shards"])
        shard_states = []
        for k in range(n):
            prefix = f"host/shard{k}/"
            shard_states.append(unpack_table_state(
                {key[len(prefix):]: v for key, v in arrays.items()
                 if key.startswith(prefix)}))
        eng = self._embed_engine
        host = eng.host
        if hasattr(host, "shards"):
            host_state = {"dim": shard_states[0]["dim"],
                          "num_shards": n, "shards": shard_states}
        else:
            host_state = shard_states[0]
        if self._embed_comm is not None:
            counters = np.asarray(arrays.get("comm/counters", [0, 0]),
                                  np.int64)
            self._embed_comm.load_state_dict(
                {"service": host_state,
                 "pushed_total": int(counters[0]),
                 "applied_total": int(counters[1])})
        else:
            host.load_state_dict(host_state)
        eng.load_state_dict(eng_state)

    def _sched(self):
        sched = getattr(self.engine.optimizer, "_learning_rate", None)
        return sched if hasattr(sched, "state_dict") else None

    def _mesh_descriptor(self):
        mesh = getattr(self.engine, "mesh", None)
        if mesh is None:
            return None
        from .topology import mesh_descriptor
        return mesh_descriptor(mesh)

    def _meta(self, step: int) -> Dict[str, Any]:
        meta = {"step": int(step), "rng": get_rng_state(),
                # host-side recovery state rides the checkpoint too:
                # replayed steps would otherwise double-feed the
                # watchdog EMA / dynamic loss scale and break the
                # replay-parity contract
                "watchdog": {"ema": self._loss_ema,
                             "warmup": self._ema_warmup}}
        # the mesh/topology descriptor makes the checkpoint ELASTIC: a
        # restore onto a different world size detects the mismatch,
        # validates the resize (data axes only) and reshards — see
        # checkpoint.load_sharded's resharding load path
        desc = self._mesh_descriptor()
        if desc is not None:
            meta["mesh"] = desc
        if self.scaler is not None:
            try:
                meta["scaler"] = {
                    k: v for k, v in self.scaler.state_dict().items()
                    if isinstance(v, (int, float, bool))}
            except Exception as e:
                warnings.warn(f"GradScaler state not checkpointed: {e}")
        sched = self._sched()
        if sched is not None:
            try:
                meta["lr_sched"] = {k: float(v) if isinstance(v, (int, float))
                                    else v
                                    for k, v in sched.state_dict().items()}
            except Exception as e:
                warnings.warn(f"LR scheduler state not checkpointed: {e}")
        if self._active_loader is not None:
            # (epoch, cursor, shuffle state) — what makes resume O(1):
            # the restored loader skips `cursor` index-batches without
            # loading a sample, instead of replaying the stream
            try:
                meta["loader"] = self._active_loader.state_dict()
            except Exception as e:
                warnings.warn(f"loader state not checkpointed ({e}); "
                              "resume will replay the stream")
        return meta

    def save(self, step: int) -> bool:
        """Drain in-flight work and atomically commit a checkpoint;
        transient write failures retry with backoff, and a save that
        still fails is *counted and survived* (training goes on from
        the previous checkpoint window)."""
        self.engine.drain()
        health.beat()  # a long drain must not read as a hang
        embed = self._embed_sidecar()  # quiesces the sparse push path

        def _do_save():
            meta = self._meta(step)
            if embed is None:
                return self.manager.save(step, self._state(), meta=meta)
            arrays, summary = embed
            meta["embed"] = summary
            return self.manager.save(step, self._state(), meta=meta,
                                     sidecars={"embed": arrays})

        t0 = time.perf_counter()
        try:
            self._retrying(_do_save, what=f"checkpoint save (step {step})")
        except Exception as e:
            self.report.checkpoint_write_failures += 1
            obs_registry.process_registry().counter(
                "ft_checkpoint_write_failures_total").inc()
            obs_events.emit("checkpoint_abandoned", step=int(step),
                            error=repr(e))
            warnings.warn(
                f"checkpoint at step {step} abandoned after "
                f"{self.max_retries} retries ({e}); continuing — the "
                f"restore window stays at step {self.manager.latest_step()}")
            return False
        dt = time.perf_counter() - t0
        self.report.checkpoints_written += 1
        m = obs_registry.process_registry()
        m.counter("ft_checkpoints_total").inc()
        m.histogram("ft_checkpoint_save_seconds").observe(dt)
        obs_events.emit("checkpoint_commit", step=int(step),
                        seconds=round(dt, 4))
        self._last_saved = int(step)
        return True

    def restore_latest(self) -> int:
        """Roll engine + RNG + LR schedule + host recovery state back to
        the newest checkpoint that verifies (falling back past corrupt
        ones). Returns the restored global step."""
        t0 = time.perf_counter()
        try:
            restored, ckpt_step = self.manager.restore(self._state())
        except FileNotFoundError as e:
            # a survivable path here: every save so far was abandoned
            # (persistent storage outage) — name the real cause instead
            # of a bare "no checkpoints" far from it
            raise CheckpointCorruptError(
                "recovery needs a checkpoint but none was ever "
                f"committed under {self.manager.directory} "
                f"({self.report.checkpoint_write_failures} abandoned "
                "write(s) this run — see the checkpoint-save warnings "
                "above)") from e
        self.engine.params = restored["params"]
        self.engine.opt_state = restored["opt_state"]
        self.engine.sync_model()
        meta = self.manager.read_meta(ckpt_step) or {}
        cur_mesh = self._mesh_descriptor()
        if cur_mesh is not None and "mesh" in meta:
            from .topology import MeshDescriptor
            saved_mesh = MeshDescriptor.from_meta(meta["mesh"])
            if saved_mesh is not None and saved_mesh != cur_mesh:
                # the load above already validated + performed the
                # old-shard → new-shard remap; count it so the elastic
                # acceptance matrix can assert the resize really took
                # the resharding path
                self.report.resharded_restores += 1
        if "rng" in meta:
            set_rng_state(meta["rng"])
        wd = meta.get("watchdog")
        if wd is not None:
            self._loss_ema = wd.get("ema")
            self._ema_warmup = int(wd.get("warmup", 0))
        if self.scaler is not None and "scaler" in meta:
            try:
                self.scaler.load_state_dict(meta["scaler"])
            except Exception as e:
                warnings.warn(f"GradScaler state not restored: {e}")
        sched = self._sched()
        if sched is not None and "lr_sched" in meta:
            try:
                sched.set_state_dict(meta["lr_sched"])
            except Exception as e:
                warnings.warn(f"LR scheduler state not restored: {e}")
        # stashed for the next _data_iter (the caller rebuilds the
        # iterator right after a restore)
        self._restored_loader_state = meta.get("loader")
        self._restore_embed(ckpt_step, meta)
        self.report.restores += 1
        m = obs_registry.process_registry()
        m.counter("ft_restores_total").inc()
        m.histogram("ft_checkpoint_restore_seconds").observe(
            time.perf_counter() - t0)
        obs_events.emit("restore", step=int(meta.get("step", ckpt_step)))
        return int(meta.get("step", ckpt_step))

    # -- retry wrapper ---------------------------------------------------

    def _retrying(self, fn: Callable[[], Any], what: str):
        """Bounded exponential backoff around a transient operation.
        Retries ``Exception`` only — interrupts (KeyboardInterrupt,
        SystemExit, SimulatedPreemption) always unwind to their real
        handler."""
        attempt = 0
        while True:
            health.beat()  # retries/backoff are liveness, not a hang
            try:
                return fn()
            except Exception as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                self.report.retries += 1
                delay = min(self.backoff_base_s * (2.0 ** (attempt - 1)),
                            self.backoff_max_s)
                warnings.warn(
                    f"{what} failed ({type(e).__name__}: {e}); "
                    f"retry {attempt}/{self.max_retries} in {delay:.2f}s")
                if delay:
                    self._sleep(delay)

    def _bump_restore_streak(self, step: int, why: str) -> None:
        """Bound EVERY restore-and-replay loop: a deterministic fault
        at one stream position (persistently bad data, a batch that
        always kills the readback) must raise after max_retries
        replays, not spin forever."""
        prev, count = self._restore_streak
        count = count + 1 if prev == step else 1
        self._restore_streak = (step, count)
        if count > max(self.max_retries, 1):
            raise BadStepError(
                f"step {step} failed {count} restore-and-replay "
                f"attempts ({why}) — the fault is deterministic, not "
                "transient; fix the input (or use "
                "bad_step_policy='skip' for bad data)")

    # -- divergence watchdog --------------------------------------------

    def _diverged(self, loss: float) -> bool:
        if self.divergence_factor <= 0:
            return False
        if self._loss_ema is None or self._ema_warmup < 5:
            self._ema_warmup += 1
            self._loss_ema = loss if self._loss_ema is None else \
                0.7 * self._loss_ema + 0.3 * loss
            return False
        if loss > self.divergence_factor * max(abs(self._loss_ema), 1e-8):
            return True
        self._loss_ema = 0.9 * self._loss_ema + 0.1 * loss
        return False

    # -- the loop --------------------------------------------------------

    def _data_iter(self, data_factory, start: int):
        """Fresh iterator over the data stream, repositioned past the
        ``start`` batches the restored checkpoint already consumed (one
        batch per global step, the resume contract).

        Two repositioning paths:

        * **O(1) state restore** — the factory handed back a
          checkpointable :class:`~paddle1_tpu.io.DataLoader` and the
          checkpoint carried its ``state_dict``: the loader re-applies
          (epoch, cursor, shuffle state) and skips ``cursor``
          *index-batches* without loading a single sample;
        * **legacy replay fast-forward** — any other iterable (or a
          checkpoint written before loader state existed): the stream
          is replayed and ``start`` batches discarded — O(steps), and
          only correct under the zero-arg-deterministic-factory
          contract. Warned once so the cost is visible.
        """
        src = data_factory()
        loader = src if _is_dataloader(src) else None
        if loader is not None:
            self._track_loader(loader)
        state = self._restored_loader_state
        self._restored_loader_state = None
        if loader is not None and loader.checkpointable():
            self._active_loader = loader
            if state is not None:
                # even at start 0 this matters: the rolled-back epoch's
                # shuffle seed must be re-applied, not re-drawn
                loader.set_state_dict(state)
                self.report.loader_state_restores += 1
                if start:
                    self.report.loader_resume = "state"
                return iter(loader)
            if start == 0:
                return iter(loader)
            # checkpoint predates loader state (or its snapshot failed):
            # fall through to the replay fast-forward
        else:
            self._active_loader = None
        it = iter(src)
        if not start:
            return it
        self.report.loader_resume = "replay"
        if not self._replay_warned:
            self._replay_warned = True
            warnings.warn(
                f"resume is replaying {start} batch(es) to reposition "
                "the data stream — the O(steps) fast-forward under the "
                "zero-arg-deterministic-factory contract; hand fit() a "
                "factory returning a checkpointable io.DataLoader for "
                "O(1) state restore")
        return itertools.islice(it, start, None)

    def _track_loader(self, loader) -> None:
        """Baseline a loader's resilience counters the first time this
        fit sees it, so the report aggregates per-fit DELTAS (the same
        loader object is typically handed back by every factory call,
        and may outlive several fits)."""
        for rec in self._seen_loaders:
            if rec[0] is loader:
                return
        self._seen_loaders.append(
            (loader, loader.bad_sample_count, len(loader.quarantine),
             loader.worker_restart_count, loader.stall_events))

    def _collect_loader_counters(self) -> None:
        for ld, bad0, quar0, rst0, stall0 in self._seen_loaders:
            self.report.bad_samples += ld.bad_sample_count - bad0
            self.report.samples_quarantined += len(ld.quarantine) - quar0
            self.report.loader_worker_restarts += \
                ld.worker_restart_count - rst0
            self.report.loader_stalls += ld.stall_events - stall0

    def fit(self, data: Callable[[], Iterable], steps: int,
            lr: Optional[float] = None) -> ResilienceReport:
        """Run up to ``steps`` global steps with checkpoints, resume,
        retries and bad-step policies. ``data`` is a zero-arg factory
        returning a fresh deterministic batch iterable — required so
        restore/resume can replay the stream from any step."""
        if not callable(data):
            raise InvalidArgumentError(
                "data must be a zero-arg factory returning a fresh "
                "batch iterable (resume/restore replay the stream); "
                "pass `lambda: loader`, not the loader itself")
        self.report = ResilienceReport()
        self._seen_loaders = []
        self._restored_loader_state = None
        if self.manager.latest_step() is not None:
            step = self.restore_latest()
            self.report.resumed_from = step
            self.report.restores -= 1  # resume-on-entry is not a rollback
            it = self._data_iter(data, step)
        else:
            step = 0
            # iterator FIRST, then the baseline: building the epoch's
            # iterator draws the shuffle seed, so the step-0 checkpoint
            # captures loader state a rollback-to-0 can replay exactly
            it = self._data_iter(data, 0)
            # a step-0 baseline guarantees restore_last_good/preemption
            # always have a rollback target, even before the first
            # periodic save
            self.save(0)
        try:
            return self._fit_loop(data, steps, lr, step, it)
        finally:
            # even when a policy raises (BadStepError, DataLoaderStalled)
            # the report the caller inspects carries the loader counters
            self._collect_loader_counters()

    def _fit_loop(self, data, steps, lr, step, it) -> ResilienceReport:
        last_loss = None
        max_step = step  # high-water mark: steps below it are replays
        while step < steps:
            try:
                # the supervisor's liveness signal: one beat per loop
                # iteration (no-op when unsupervised). Also the trigger
                # for worker-level chaos (worker_kill/hang/unhealthy).
                health.beat()
                chaos.check_preempt()
                try:
                    batch = next(it)
                except StopIteration:
                    break  # stream exhausted before `steps`
                if chaos.enabled():
                    batch = chaos.maybe_poison(batch)

                # Two distinct retry surfaces with different semantics:
                # a DISPATCH failure applied nothing, so re-running the
                # step is safe; a READBACK failure arrives after the
                # update may already have landed on device, so only the
                # fetch is retried (the future re-fetches on failure —
                # it caches on success only). If the readback never
                # succeeds the step's outcome is unknown: roll back to
                # certainty instead of guessing.
                fut = self._retrying(
                    lambda: self.engine.step(batch, lr),
                    what=f"train step {step} dispatch")
                try:
                    loss = self._retrying(
                        lambda: float(fut),  # one packed fetch: loss+flag
                        what=f"train step {step} readback")
                except Exception as e:
                    warnings.warn(
                        f"step {step} outcome unknown (readback failed "
                        f"after dispatch: {e}); restoring last good "
                        "checkpoint")
                    self._bump_restore_streak(
                        step, f"readback failure ({e})")
                    step = self.restore_latest()
                    it = self._data_iter(data, step)
                    continue
                bad = fut.bad
                diverged = False
                if not bad and self._diverged(loss):
                    diverged = True
                    self.report.divergence_trips += 1
                if bad or diverged:
                    self.report.bad_steps += 1
                    obs_registry.process_registry().counter(
                        "ft_bad_steps_total").inc()
                    if self.scaler is not None:
                        self.scaler.record_step(found_inf=True)
                    step, it = self._handle_bad_step(
                        step, diverged, loss, data, it)
                else:
                    if self.scaler is not None:
                        self.scaler.record_step(found_inf=False)
                    step += 1
                    if step > max_step:
                        max_step = step
                        self.report.steps_done += 1
                    else:  # re-applying work a rollback rewound past
                        self.report.steps_replayed += 1
                    last_loss = loss
                # the periodic-save check sits OUTSIDE the good/bad
                # branch: a skipped bad step that lands on a save
                # boundary must not silently double the rollback window
                if self.save_freq and step % self.save_freq == 0 \
                        and 0 < step < steps \
                        and self._last_saved != step:
                    self.save(step)
            except chaos.SimulatedPreemption as e:
                self.report.preemptions += 1
                obs_registry.process_registry().counter(
                    "ft_preemptions_total").inc()
                obs_events.emit("preemption", step=int(step),
                                graceful=bool(getattr(e, "graceful",
                                                      False)))
                if getattr(e, "graceful", False):
                    # an advance NOTICE (SIGTERM grace window): the
                    # current params are known-good — checkpoint them
                    # NOW so the next incarnation loses nothing, then
                    # keep training until actually killed — unless the
                    # notice was a supervisor DRAIN, whose contract is
                    # checkpoint-then-stop (the pod is being wound
                    # down, not preempted out from under us)
                    self.save(step)
                    if health.drain_requested():
                        break
                    continue
                # ungraceful (simulated kill): roll back and replay
                step = self.restore_latest()
                it = self._data_iter(data, step)
        if self._last_saved != step:
            # skip when the last act WAS saving this step (drain, or a
            # run ending on a save boundary): the rename-aside re-save
            # would waste the drain grace window and briefly demote the
            # committed checkpoint
            self.save(step)
        self.engine.sync_model()
        self.report.final_step = step
        self.report.final_loss = last_loss
        return self.report

    def _handle_bad_step(self, step: int, diverged: bool, loss: float,
                         data, it):
        """Apply the bad-step policy; returns the (possibly rewound)
        (step, iterator)."""
        kind = "diverged" if diverged else "non-finite"
        if self.policy == "raise":
            raise BadStepError(
                f"{kind} training step at global step {step} "
                f"(loss={loss}); params keep their last good values — "
                "set bad_step_policy='skip' or 'restore_last_good' to "
                "continue through this automatically")
        if self.policy == "skip" and not diverged:
            # update already skipped on device; consume the slot
            self.report.steps_skipped += 1
            return step + 1, it
        # restore_last_good — and the only sound treatment of a
        # diverged-but-finite step (its update was applied on device)
        self._bump_restore_streak(step, f"{kind} data")
        warnings.warn(
            f"{kind} step at global step {step}: restoring last good "
            "checkpoint and replaying")
        new_step = self.restore_latest()
        return new_step, self._data_iter(data, new_step)
