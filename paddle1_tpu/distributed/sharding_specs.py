"""Parameter/activation sharding-spec collection for pjit.

This is the TPU replacement for the reference's program-rewriting
meta-optimizers (SURVEY §2.3): instead of inserting c_allreduce/c_broadcast
ops into a ProgramDesc, we collect ``PartitionSpec``s from layer metadata
(``Parameter.sharding_axes`` written by the meta_parallel layers) plus the
ZeRO policy, hand them to ``jax.jit(..., in_shardings=...)`` over the hybrid
mesh, and let GSPMD emit the collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layer_base import Layer

__all__ = ["param_partition_specs", "named_shardings", "zero_shard_spec",
           "data_partition_spec", "describe_layout"]


def describe_layout(tree) -> Dict[str, str]:
    """{leaf path: partition spec} of a live (or abstract-with-sharding)
    state tree — how the state is actually laid out over the mesh.

    The elastic-resize surface: after a resharding restore
    (``checkpoint.load_sharded`` onto a new world size) this is the
    quick way to see — and, in the tests, assert — which leaves landed
    sharded and which fell back to replicated (a dim the new degree no
    longer divides). Host-only leaves are skipped.
    """
    out: Dict[str, str] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            out[jax.tree_util.keystr(path)] = str(spec)
    return out


def param_partition_specs(layer: Layer,
                          zero_stage: int = 0,
                          zero_axis: str = "sharding",
                          zero_axis_size: int = 1) -> Dict[str, P]:
    """{param_name: PartitionSpec}. TP axes come from the layer metadata;
    ZeRO stage-3 additionally shards the largest unsharded dim over the
    sharding axis (stages 1/2 shard only optimizer state / grads — see
    zero_shard_spec). Dims not divisible by ``zero_axis_size`` stay
    replicated (small biases etc.)."""
    specs: Dict[str, P] = {}
    for name, p in layer.state_dict().items():
        axes = list(getattr(p, "sharding_axes", None) or
                    [None] * len(p.shape))
        while len(axes) < len(p.shape):
            axes.append(None)
        if zero_stage >= 3 and zero_axis not in axes and p.shape:
            free = [i for i, a in enumerate(axes)
                    if a is None and p.shape[i] % max(zero_axis_size, 1) == 0]
            if free:
                big = max(free, key=lambda i: p.shape[i])
                axes[big] = zero_axis
        specs[name] = P(*axes)
    return specs


def zero_shard_spec(param_spec: P, shape, zero_axis: str = "sharding",
                    zero_axis_size: int = 1) -> P:
    """Spec for optimizer slot variables under ZeRO stage>=1: slots shard
    over the sharding axis on the largest dim not already sharded (the
    reference's sharding_optimizer assigns whole params to owner ranks;
    GSPMD's per-dim sharding is strictly more uniform). Non-divisible dims
    stay replicated."""
    axes = list(param_spec) if param_spec else []
    while len(axes) < len(shape):
        axes.append(None)
    if zero_axis in axes or not shape:
        return P(*axes)
    free = [i for i, a in enumerate(axes)
            if a is None and shape[i] % max(zero_axis_size, 1) == 0]
    if not free:
        return P(*axes)
    big = max(free, key=lambda i: shape[i])
    axes[big] = zero_axis
    return P(*axes)


def data_partition_spec(batch_axes=("dp", "sharding"),
                        seq_axis: Optional[str] = None) -> P:
    """Batch tensors: batch dim over dp (and the sharding axis, which in
    hybrid-ZeRO also carries data), optional sequence dim over sp."""
    if seq_axis:
        return P(tuple(batch_axes), seq_axis)
    return P(tuple(batch_axes))


def named_shardings(mesh: Mesh, specs: Dict[str, P]
                    ) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, s) for k, s in specs.items()}
