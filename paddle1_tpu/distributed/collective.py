"""Collective communication API.

Analog of the reference's ``python/paddle/distributed/collective.py``
(new_group :198, broadcast :330, all_reduce :397, all_gather :572, scatter
:650, barrier :158, TP internals _c_identity/_c_concat/_c_split :732-813)
and the collective op layer (`paddle/fluid/operators/collective/` — the
c_allreduce_sum / c_allgather / send_v2 / recv_v2 kernels over NCCL).

TPU-native design: a collective is not a kernel against a comm handle — it is
a *named-axis operation inside an SPMD trace*. Under ``shard_map`` over a
``Mesh`` axis, these functions lower to ``lax.psum``/``all_gather``/
``ppermute`` etc., which XLA compiles to ICI collectives. Outside a trace
(eager, single process) they act on the process group: world-size-1 groups
are identity — mirroring the reference's behavior where collectives on a
single rank are no-ops — and the simulated-mesh test backend (see
tests/test_collective.py) exercises the real multi-device lowering on a
virtual CPU mesh, which the reference could not do (SURVEY §4).

Autograd: each collective goes through ``engine.apply`` so it is recorded on
the eager tape with the correct XLA-derived vjp (psum ↔ psum, all_gather ↔
reduce_scatter, ppermute ↔ inverse ppermute).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd.engine import apply
from ..core import chaos, collective_sanitizer
from ..core.errors import InvalidArgumentError, PreconditionNotMetError
from ..core.tensor import Tensor, to_tensor
from . import env
from .topology import _AxisGroup

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "destroy_process_group",
           "hierarchical_all_reduce",
           "is_initialized", "all_reduce", "all_gather", "all_gather_object",
           "reduce", "broadcast", "scatter", "reduce_scatter", "alltoall",
           "all_to_all", "send", "recv", "isend", "irecv", "barrier", "wait",
           "get_rank", "get_world_size", "_c_identity", "_c_concat",
           "_c_split", "split"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _pprod(x, axis):
    """Product over a mesh axis via log-magnitude psum + sign/zero tracking
    (XLA has no native product collective; exp∘psum∘log alone NaNs on
    negatives and -infs on zeros)."""
    mag = jnp.exp(lax.psum(jnp.log(jnp.where(x == 0, 1.0, jnp.abs(x))),
                           axis))
    neg = lax.psum((x < 0).astype(jnp.int32), axis)
    has_zero = lax.psum((x == 0).astype(jnp.int32), axis) > 0
    sign = jnp.where(neg % 2 == 0, 1.0, -1.0)
    return jnp.where(has_zero, 0.0, sign * mag).astype(x.dtype)


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.PROD: _pprod,
}


class Group:
    """A communicator group (reference collective.py Group). On TPU a group
    is (axis_name | explicit rank list); inside SPMD traces only axis-bound
    groups are meaningful."""

    def __init__(self, rank: int, nranks: int, gid: int = 0,
                 ranks: Optional[List[int]] = None,
                 axis: Optional[str] = None):
        self.rank = rank
        self.nranks = nranks
        self.id = gid
        self.ranks = ranks if ranks is not None else list(range(nranks))
        self.axis = axis

    @property
    def world_size(self) -> int:
        return self.nranks

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return (f"Group(rank={self.rank}, nranks={self.nranks}, "
                f"id={self.id}, axis={self.axis!r})")


_group_lock = threading.Lock()
_group_map: Dict[int, Group] = {}
_next_gid = [1]
_default_group: Optional[Group] = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        ws = env.get_world_size()
        _default_group = Group(env.get_rank(), ws, gid=0,
                               ranks=list(range(ws)),
                               axis=env.current_spmd_axis("dp"))
        _group_map[0] = _default_group
    return _default_group


def is_initialized() -> bool:
    return _default_group is not None


def destroy_process_group(group: Optional[Group] = None) -> None:
    global _default_group
    with _group_lock:
        if group is None:
            _group_map.clear()
            _default_group = None
        else:
            _group_map.pop(group.id, None)


def new_group(ranks: Optional[Sequence[int]] = None, backend: Optional[str]
              = None, axis: Optional[str] = None) -> Group:
    """Create a comm group (reference collective.py:198 — there it spawns an
    NCCL ring per group; here a group is an axis handle / rank subset)."""
    with _group_lock:
        gid = _next_gid[0]
        _next_gid[0] += 1
    me = env.get_rank()
    ranks = sorted(ranks) if ranks is not None else \
        list(range(env.get_world_size()))
    grank = ranks.index(me) if me in ranks else -1
    g = Group(grank, len(ranks), gid=gid, ranks=ranks, axis=axis)
    _group_map[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _get_default_group()
    if gid not in _group_map:
        raise PreconditionNotMetError(f"Group {gid} not created")
    return _group_map[gid]


def get_rank(group: Optional[Group] = None) -> int:
    return group.rank if group is not None else env.get_rank()


def get_world_size(group: Optional[Group] = None) -> int:
    return group.nranks if group is not None else env.get_world_size()


# ---------------------------------------------------------------------------
# axis resolution
# ---------------------------------------------------------------------------


def _resolve_axis(group, default_logical: str = "dp") -> Optional[str]:
    """Mesh-axis name for this collective: explicit group axis > thread-bound
    SPMD axis mapping > None (eager/no-op path)."""
    if isinstance(group, _AxisGroup):
        return group.axis
    if isinstance(group, Group) and group.axis is not None:
        return group.axis
    if isinstance(group, str):
        return group
    return env.current_spmd_axis(default_logical)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _nranks(group) -> int:
    if isinstance(group, (_AxisGroup, Group)):
        return group.nranks
    return env.get_world_size()


def _assign(tensor: Tensor, result: Tensor) -> Tensor:
    """In-place update semantics: the reference's collectives mutate their
    input var; we swap the produced value/grad-node into the same Tensor."""
    tensor._replace_impl(result)
    return tensor


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


def _skip_collective(op: str, args) -> bool:
    """Per-wrapper entry for the SPMD-discipline runtime (ISSUE 14):
    journals this op into the collective-schedule sanitizer (site = the
    USER'S call line; free when the flag is off) and returns True when
    an armed ``collective_skip`` chaos point says THIS rank skips it —
    the wrapper then returns its input untouched and journals nothing,
    seeding the rank-divergent schedule the cross-rank verifier must
    catch. Both checks are one bool test when nothing is armed."""
    if chaos.enabled() and chaos.check_collective(env.get_rank()):
        return True
    # depth 3: note_collective <- here <- wrapper <- USER call site
    collective_sanitizer.note_collective(op, args, depth=3)
    return False


def all_reduce(tensor: Tensor, op: int = ReduceOp.SUM,
               group: Optional[Group] = None, sync_op: bool = True) -> Tensor:
    """In-place all-reduce (reference collective.py:397 → c_allreduce_sum
    kernel c_allreduce_op.h:253). Under SPMD trace → lax.psum over the
    group's mesh axis."""
    if _skip_collective("all_reduce", (tensor,)):
        return tensor
    axis = _resolve_axis(group)

    def f(x):
        if axis is not None and _in_trace(x):
            if op == ReduceOp.AVG:
                return lax.pmean(x, axis)
            return _REDUCERS[op](x, axis)
        return x  # world-size-1 eager: identity

    return _assign(tensor, apply("all_reduce", f, (tensor,)))


def reduce(tensor: Tensor, dst: int = 0, op: int = ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True) -> Tensor:
    """Reduce-to-root. XLA has no single-destination reduce on a mesh axis;
    all-reduce and mask is the idiomatic (and on ICI, equal-cost ring) form."""
    if _skip_collective("reduce", (tensor,)):
        return tensor
    axis = _resolve_axis(group)

    def f(x):
        if axis is not None and _in_trace(x):
            if op == ReduceOp.AVG:
                red = lax.pmean(x, axis)
            else:
                red = _REDUCERS[op](x, axis)
            idx = lax.axis_index(axis)
            return jnp.where(idx == dst, red, x)
        return x

    return _assign(tensor, apply("reduce", f, (tensor,)))


def broadcast(tensor: Tensor, src: int = 0,
              group: Optional[Group] = None, sync_op: bool = True) -> Tensor:
    """Broadcast from group-rank ``src`` (reference collective.py:330 →
    c_broadcast). In-graph form: select src's shard and psum the rest away."""
    if _skip_collective("broadcast", (tensor,)):
        return tensor
    axis = _resolve_axis(group)

    def f(x):
        if axis is not None and _in_trace(x):
            idx = lax.axis_index(axis)
            masked = jnp.where(idx == src, x, jnp.zeros_like(x))
            return lax.psum(masked, axis)
        return x

    return _assign(tensor, apply("broadcast", f, (tensor,)))


def all_gather(tensor_list: Optional[List[Tensor]], tensor: Tensor,
               group: Optional[Group] = None, sync_op: bool = True):
    """Gather shards from every rank (reference collective.py:572 →
    c_allgather). Appends per-rank tensors to ``tensor_list``; also returns
    the stacked result for functional use."""
    if _skip_collective("all_gather", (tensor,)):
        if tensor_list is not None:
            tensor_list.append(tensor)
        return tensor
    axis = _resolve_axis(group)
    n = _nranks(group)

    def f(x):
        if axis is not None and _in_trace(x):
            return lax.all_gather(x, axis, axis=0)  # [n, ...]
        return jnp.expand_dims(x, 0)

    stacked = apply("all_gather", f, (tensor,))
    if tensor_list is not None:
        from ..ops import manip_ops
        parts = manip_ops.unstack(stacked, axis=0)
        tensor_list.extend(parts)
    return stacked


def all_gather_object(object_list: list, obj: Any,
                      group: Optional[Group] = None):
    """Single-process world: the object itself (multi-host object gather
    rides the coordination service, not ICI)."""
    object_list.extend([obj] * _nranks(group))


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list,
                   op: int = ReduceOp.SUM, group: Optional[Group] = None,
                   sync_op: bool = True) -> Tensor:
    """Reduce-scatter (reference c_reducescatter op). Input: concatenated
    [n*chunk, ...] or list of n tensors; output shard into ``tensor``."""
    if _skip_collective("reduce_scatter", (tensor,)):
        return tensor
    axis = _resolve_axis(group)
    if isinstance(tensor_or_tensor_list, (list, tuple)):
        from ..ops import manip_ops
        src = manip_ops.concat(list(tensor_or_tensor_list), axis=0)
    else:
        src = tensor_or_tensor_list
    n = _nranks(group)

    def f(x):
        if axis is not None and _in_trace(x):
            return lax.psum_scatter(x, axis, scatter_dimension=0,
                                    tiled=True)
        return x

    return _assign(tensor, apply("reduce_scatter", f, (src,)))


def scatter(tensor: Tensor, tensor_list: Optional[List[Tensor]] = None,
            src: int = 0, group: Optional[Group] = None,
            sync_op: bool = True) -> Tensor:
    """Scatter list from src (reference collective.py:650 → c_scatter:
    broadcast + slice by rank)."""
    if _skip_collective("scatter", (tensor,)):
        return tensor
    axis = _resolve_axis(group)
    if tensor_list:
        from ..ops import manip_ops
        stacked = manip_ops.stack(tensor_list, axis=0)

        def f(x):
            if axis is not None and _in_trace(x):
                idx = lax.axis_index(axis)
                full = lax.psum(jnp.where(lax.axis_index(axis) == src, x,
                                          jnp.zeros_like(x)), axis)
                return lax.dynamic_index_in_dim(full, idx, 0,
                                                keepdims=False)
            return x[0]

        return _assign(tensor, apply("scatter", f, (stacked,)))
    return tensor


def alltoall(in_tensor_list, out_tensor_list: Optional[list] = None,
             group: Optional[Group] = None, sync_op: bool = True):
    """All-to-all (reference operators/collective/alltoall_op). Accepts a
    list of n tensors (one per peer) or a single [n*chunk,...] tensor; under
    trace lowers to lax.all_to_all over the axis."""
    if _skip_collective("alltoall", (in_tensor_list,)):
        if out_tensor_list is not None and isinstance(
                in_tensor_list, (list, tuple)):
            out_tensor_list.extend(in_tensor_list)
        return in_tensor_list
    axis = _resolve_axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..ops import manip_ops
        src = manip_ops.stack(list(in_tensor_list), axis=0)  # [n, ...]
    else:
        src = in_tensor_list

    def f(x):
        if axis is not None and _in_trace(x):
            return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        return x

    out = apply("alltoall", f, (src,))
    if out_tensor_list is not None:
        from ..ops import manip_ops
        out_tensor_list.extend(manip_ops.unstack(out, axis=0))
    return out


all_to_all = alltoall


def send(tensor: Tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> None:
    """P2P send (reference send_v2 — pipeline edges). In-graph equivalent is
    ``ppermute``; use paddle1_tpu.distributed.p2p.ppermute inside pipeline
    schedules. Eager single-process: buffered locally."""
    _p2p_buffer.setdefault(dst, []).append(tensor)


def recv(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> Tensor:
    """P2P recv (reference recv_v2)."""
    me = env.get_rank()
    buf = _p2p_buffer.get(me, [])
    if buf:
        return _assign(tensor, buf.pop(0))
    return tensor


_p2p_buffer: Dict[int, List[Tensor]] = {}


class _Work:
    def wait(self):
        return None

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _Work()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _Work()


def barrier(group: Optional[Group] = None) -> None:
    """Reference collective.py:158 barrier op. XLA programs are globally
    scheduled, so in-graph barriers are unnecessary; across hosts this
    syncs via the coordination service when multi-process."""
    if _skip_collective("barrier", ()):
        return
    try:
        if jax.process_count() > 1:
            from jax.experimental.multihost_utils import \
                sync_global_devices
            # best-effort by design: single-host runs have no
            # coordination service (the sync raising there must not
            # fail the barrier API), and a real multi-host init
            # failure already surfaced at jax.distributed.initialize
            sync_global_devices("paddle1_tpu_barrier")  # noqa: collective-swallow — see note
    except Exception:
        pass


def wait(tensor: Tensor, group: Optional[Group] = None,
         use_calc_stream: bool = True) -> None:
    """Reference c_wait_comm/c_wait_compute — stream ordering. XLA's token
    ordering makes this a no-op; kept for API parity."""
    return None


# ---------------------------------------------------------------------------
# TP internals (reference collective.py:732-813)
# ---------------------------------------------------------------------------


def _c_identity(tensor: Tensor, group: Optional[Group] = None,
                skip_c_identity_dynamic: bool = False) -> Tensor:
    """Forward identity / backward all-reduce (the f operator of Megatron).
    Reference collective.py:732."""
    axis = _resolve_axis(group, "mp")

    def f(x):
        if axis is not None and _in_trace(x):
            # identity fwd; psum in bwd comes from custom vjp
            return _ident_psum_bwd(x, axis)
        return x

    return apply("c_identity", f, (tensor,))


def _ident_psum_bwd(x, axis):
    @jax.custom_vjp
    def ident(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (lax.psum(g, axis),)

    ident.defvjp(fwd, bwd)
    return ident(x)


def _psum_ident_bwd(x, axis):
    @jax.custom_vjp
    def red(x):
        return lax.psum(x, axis)

    def fwd(x):
        return lax.psum(x, axis), None

    def bwd(_, g):
        return (g,)

    red.defvjp(fwd, bwd)
    return red(x)


def _mp_allreduce(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """Forward all-reduce / backward identity (the g operator of Megatron).
    Reference mp_ops c_allreduce_sum with use_model_parallel=True."""
    axis = _resolve_axis(group, "mp")

    def f(x):
        if axis is not None and _in_trace(x):
            return _psum_ident_bwd(x, axis)
        return x

    return apply("mp_allreduce", f, (tensor,))


def _c_concat(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """All-gather along the last dim (reference collective.py:770 c_concat:
    column-parallel output gather)."""
    axis = _resolve_axis(group, "mp")

    def f(x):
        if axis is not None and _in_trace(x):
            return lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)
        return x

    return apply("c_concat", f, (tensor,))


def _c_split(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """Take this rank's slice of the last dim (reference collective.py:813
    c_split — row-parallel input scatter)."""
    axis = _resolve_axis(group, "mp")
    n = _nranks(group)

    def f(x):
        if axis is not None and _in_trace(x):
            n_ranks = lax.axis_size(axis)
            if x.shape[-1] % n_ranks != 0:
                raise InvalidArgumentError(
                    f"c_split: last dim {x.shape[-1]} not divisible by "
                    f"axis '{axis}' size {n_ranks}")
            idx = lax.axis_index(axis)
            chunk = x.shape[-1] // n_ranks
            return lax.dynamic_slice_in_dim(x, idx * chunk, chunk,
                                            axis=x.ndim - 1)
        return x

    return apply("c_split", f, (tensor,))


def split(x, num_or_sections, axis=0, group=None):
    """paddle.distributed.split — deprecated TP helper; use meta_parallel
    layers. Only the last-dim even split (the c_split semantics) is
    supported; anything else raises rather than silently mis-slicing."""
    ndim = len(x.shape)
    if axis not in (-1, ndim - 1):
        raise InvalidArgumentError(
            "paddle1_tpu.distributed.split only supports splitting the "
            "last dim over mp (c_split); for other layouts use "
            "distributed.fleet ColumnParallelLinear/RowParallelLinear")
    n = _nranks(group)
    if isinstance(num_or_sections, int) and num_or_sections not in (n, -1):
        raise InvalidArgumentError(
            f"split num_or_sections={num_or_sections} must equal the "
            f"group size {n}")
    return _c_split(x, group)


def hierarchical_all_reduce(x, intra_axis: str, inter_axis: str):
    """Two-level all-reduce for multi-slice meshes (the functional form
    of the reference's hierarchical_allreduce strategy toggle,
    distributed_strategy.py proto :146-196: intra-node reduce →
    inter-node allreduce over node leaders → intra-node broadcast).

    TPU-native mapping over a mesh with a fast axis (ICI, within a
    slice) and a slow axis (DCN, across slices): reduce-scatter over
    ``intra_axis`` so each chip owns 1/n of the payload, all-reduce the
    shards over ``inter_axis`` (the only traffic that crosses DCN —
    bandwidth-optimal: payload/n per chip instead of the full payload),
    then all-gather back over ``intra_axis``. Call inside a shard_map
    over both axes; when dim 0 is not divisible by the intra size the
    op falls back to the flat two-axis psum (correct, more DCN bytes).

    For jit/GSPMD code, multi-axis ``psum`` already lowers
    hierarchically per the mesh topology — this explicit form exists
    for shard_map code paths and for strategy parity.
    """
    if _skip_collective("hierarchical_all_reduce", (x,)):
        return x
    import jax

    def f(v):
        if not _in_trace(v):
            return v  # single-process eager: identity
        n = lax.axis_size(intra_axis)
        if v.ndim >= 1 and v.shape[0] % n == 0:
            shard = lax.psum_scatter(v, intra_axis, scatter_dimension=0,
                                     tiled=True)
            shard = lax.psum(shard, inter_axis)
            return lax.all_gather(shard, intra_axis, axis=0, tiled=True)
        return lax.psum(lax.psum(v, intra_axis), inter_axis)

    if isinstance(x, Tensor):
        return apply("hierarchical_all_reduce", f, (x,))
    return f(x)
