"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference snapshot has NO sequence parallelism (SURVEY §5 long-context:
absent) — this is the capability-extension target the TPU build adds as a
first-class mesh axis ('sp'). Two schemes, both pure-jax functions intended
to run under ``shard_map`` over the hybrid mesh (or inside a pjit with
explicit sp sharding):

* **ring_attention(q, k, v, axis_name)** — K/V shards rotate around the
  ICI ring via ``lax.ppermute`` while each device's queries accumulate
  online-softmax partials; peak memory is one K/V shard, comm fully
  overlaps compute on TPU (the ppermute for step i+1 is independent of the
  step-i matmuls, so XLA's latency-hiding scheduler pipelines them).
* **ulysses_attention(q, k, v, axis_name)** — all-to-all swaps the shard
  axis from sequence to heads, runs dense local attention (flash kernel
  when aligned), and swaps back. Cheaper for moderate sequence lengths;
  requires num_heads % sp == 0.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ulysses_attention"]

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask=None):
    """One q-shard x k-shard partial: returns (numerator, sumexp, rowmax).
    q,k,v: [B, N, H, D] shards; f32 math."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                         # [B,H,Nq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                         # [B,H,Nq]
    num = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return num, l, m


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """[B, N_local, H, D] per device; sequence sharded over ``axis_name``.

    Each of the sp steps computes local-q x rotating-KV partials and merges
    them with the running online-softmax state; ppermute advances the K/V
    ring one ICI neighbor per step.
    """
    d = q.shape[-1]
    sc = float(scale) if scale is not None else float(1.0 / (d ** 0.5))
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, nl, h, _ = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    q_pos = my * nl + jax.lax.broadcasted_iota(jnp.int32, (nl, 1), 0)

    def step(carry, i):
        k_cur, v_cur, m_run, l_run, acc = carry
        src = (my - i) % n  # whose shard we hold at step i
        mask = None
        if causal:
            k_pos = src * nl + jax.lax.broadcasted_iota(
                jnp.int32, (1, nl), 1)
            mask = (q_pos >= k_pos)[None, None]      # [1,1,Nq,Nk]
        num, l, m = _block_attn(q, k_cur, v_cur, sc, mask)
        m_new = jnp.maximum(m_run, m)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m - m_new)
        l_new = l_run * alpha + l * beta
        acc_new = acc * alpha[..., None] + num * beta[..., None]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, nl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, nl), jnp.float32)
    acc0 = jnp.zeros((b, h, nl, d), jnp.float32)
    # Mark the running-softmax carries device-varying so the scan carry
    # type matches (k/v rotate, so the whole carry is varying over sp).
    m0, l0, acc0 = (lax.pvary(x, (axis_name,)) for x in (m0, l0, acc0))
    (_, _, m, l, acc), _ = lax.scan(step, (k, v, m0, l0, acc0),
                                    jnp.arange(n))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]                    # [B,H,Nq,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): shard
    axis moves seq→heads, local attention runs over the FULL sequence with
    H/sp heads, then moves back. [B, N_local, H, D] in and out."""
    n = lax.axis_size(axis_name)
    b, nl, h, d = q.shape
    if h % n:
        raise ValueError(f"ulysses: num_heads {h} not divisible by sp={n}")

    def seq2head(x):
        # [B, Nl, H, D] -> [B, Nl*n(seq global), H/n, D]
        x = x.reshape(b, nl, n, h // n, d)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                           tiled=False)
        return x.reshape(b, nl * n, h // n, d)

    def head2seq(x):
        # [B, N_global, H/n, D] -> [B, n, Nl, H/n, D]; a2a removes the n
        # axis and re-inserts it before the head dim -> [B, Nl, n, H/n, D]
        x = x.reshape(b, n, nl, h // n, d)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                           tiled=False)
        return x.reshape(b, nl, h, d)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    from ..ops.pallas import flash_attention as fa
    from ..nn.functional.attention import attention_ref, use_flash_for
    # same dense-vs-flash policy as scaled_dot_product_attention (r5:
    # XLA dense wins at compute-bound lengths; flash is the
    # long-sequence memory escape) applied to the post-all-to-all
    # GLOBAL sequence length
    if fa.supported(qg.shape, kg.shape) and use_flash_for(qg, kg):
        og = fa.flash_attention(qg, kg, vg, causal=causal, scale=scale)
    else:
        og = attention_ref(qg, kg, vg, is_causal=causal, scale=scale)
    return head2seq(og)
