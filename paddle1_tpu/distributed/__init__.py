"""paddle1_tpu.distributed — fleet-style distributed training over device
meshes (reference python/paddle/distributed analog).

TPU-native architecture: one nd ``jax.sharding.Mesh`` with named axes
(pp, dp, sharding, mp, sp) replaces the reference's NCCL ring registry;
collectives are named-axis ops lowered by XLA to ICI; process bootstrap is
the JAX coordination service instead of raw-TCP ncclUniqueId broadcast.
"""

from . import env
from .env import get_rank, get_world_size, spmd_axes, current_spmd_axis
from .collective import (ReduceOp, Group, all_gather, all_gather_object,
                         hierarchical_all_reduce,
                         all_reduce, alltoall, all_to_all, barrier,
                         broadcast, destroy_process_group, get_group,
                         irecv, is_initialized, isend, new_group, recv,
                         reduce, reduce_scatter, scatter, send, split, wait)
from .parallel import (DataParallel, ParallelEnv, init_parallel_env)
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       MeshDescriptor, ReshardError, build_mesh,
                       ensure_reshardable, get_hybrid_communicate_group,
                       mesh_descriptor, plan_resize,
                       set_hybrid_communicate_group)
from . import pipeline
from .pipeline import pipeline_apply, stack_stage_params
from . import sharding_specs
from . import sequence_parallel
from .sequence_parallel import ring_attention, ulysses_attention
from .parallel_engine import ParallelEngine, make_train_step
from .spawn import spawn
from . import ps
from .ps import (DenseTable, DistributedEmbedding, EmbeddingService,
                 SparseTable)
from . import ps_server
from .ps_server import RemoteTable, TableServer, remote_service
from . import communicator
from .communicator import (AsyncCommunicator, DenseEndpoint,
                           GeoCommunicator, SparseAsyncCommunicator)
from . import checkpoint
from .checkpoint import CheckpointManager, load_sharded, save_sharded
from . import resilience
from .resilience import BadStepError, ResilienceReport, ResilientTrainer
from . import supervisor
from .supervisor import MpProcessHandle, Supervisor, SupervisorReport
from . import graph_table
from .graph_table import GraphTable
from . import hbm_embedding
from .hbm_embedding import HBMShardedEmbedding, hash_bucket
from . import embedding_engine
from .embedding_engine import ShardedEmbeddingEngine
from . import embedding_delta
from .embedding_delta import DeltaLog, DeltaRecord, DeltaSubscriber


def __getattr__(name):
    # `launch` resolves lazily so `python -m paddle1_tpu.distributed.launch`
    # doesn't trip runpy's already-imported warning. Return the MODULE (the
    # reference's paddle.distributed.launch is a module too) so the binding
    # is identical whether resolved here or by a direct submodule import.
    if name == "launch":
        from . import launch as _launch_mod
        return _launch_mod
    if name == "fleet":
        # lazy: fleet pulls in the meta-optimizer stack; resolving it on
        # first touch keeps `import paddle1_tpu` light. import_module (not
        # `from . import`) — the latter re-enters this __getattr__ via
        # _handle_fromlist before the submodule binds.
        import importlib
        return importlib.import_module(".fleet", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = ["env", "get_rank", "get_world_size", "spmd_axes",
           "current_spmd_axis", "ReduceOp", "Group", "all_gather",
           "all_gather_object", "all_reduce", "alltoall", "all_to_all",
           "barrier", "broadcast", "destroy_process_group", "get_group",
           "irecv", "is_initialized", "isend", "new_group", "recv",
           "reduce", "reduce_scatter", "scatter", "send", "split", "wait",
           "DataParallel", "ParallelEnv", "init_parallel_env",
           "CommunicateTopology", "HybridCommunicateGroup", "build_mesh",
           "get_hybrid_communicate_group", "set_hybrid_communicate_group",
           "sharding_specs", "spawn", "launch", "ParallelEngine",
           "make_train_step", "sequence_parallel", "ring_attention",
           "ulysses_attention", "pipeline", "pipeline_apply",
           "stack_stage_params",
           "ps", "SparseTable", "EmbeddingService", "DistributedEmbedding",
           "ps_server", "TableServer", "RemoteTable", "remote_service",
           "checkpoint", "CheckpointManager", "save_sharded",
           "load_sharded", "resilience", "ResilientTrainer",
           "ResilienceReport", "BadStepError", "graph_table", "GraphTable",
           "HBMShardedEmbedding", "hash_bucket", "embedding_engine",
           "ShardedEmbeddingEngine", "embedding_delta", "DeltaLog",
           "DeltaRecord", "DeltaSubscriber", "communicator",
           "AsyncCommunicator", "GeoCommunicator",
           "SparseAsyncCommunicator", "DenseEndpoint", "DenseTable"]


# -- PS-era dataset + sparse-table entry configs (reference
# distributed/__init__.py re-exports) ---------------------------------------

from ..io.file_dataset import InMemoryDataset, QueueDataset  # noqa: E402


class _EntryConfig:
    """Sparse-table entry admission policy (reference
    distributed/entry_attr.py): serialized into the table config the
    PS applies when admitting new embedding rows."""

    def _to_attr(self):
        raise NotImplementedError


class CountFilterEntry(_EntryConfig):
    """Admit a sparse feature only after it has been seen
    ``count_filter`` times (entry_attr.py CountFilterEntry)."""

    def __init__(self, count_filter: int):
        if count_filter < 0:
            raise ValueError(
                "count_filter must be >= 0 (reference check)")
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ProbabilityEntry(_EntryConfig):
    """Admit a new sparse feature with probability ``probability``
    (entry_attr.py ProbabilityEntry)."""

    def __init__(self, probability: float):
        if not 0 <= probability <= 1:
            raise ValueError(
                "probability must be in [0, 1] (reference check)")
        self._probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


__all__ += ["InMemoryDataset", "QueueDataset", "CountFilterEntry",
            "ProbabilityEntry"]
