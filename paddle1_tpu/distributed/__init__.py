"""paddle1_tpu.distributed — fleet-style distributed training over device
meshes (reference python/paddle/distributed analog).

Collective API, fleet facade, launchers, and hybrid-parallel layers land in
build stage 5-6 (SURVEY §7); env/rank plumbing is live now.
"""

from . import env
from .env import get_rank, get_world_size
