"""Hybrid-parallel topology → one nd ``jax.sharding.Mesh`` with named axes.

Analog of the reference's ``CommunicateTopology``/``HybridCommunicateGroup``
(/root/reference/python/paddle/distributed/fleet/base/topology.py:35,111),
which builds a cartesian rank mesh over (dp, pp, sharding, mp) and creates an
NCCL comm group per axis. On TPU the whole abstraction collapses onto
``jax.sharding.Mesh``: one global device mesh whose *named axes* are the
parallelism dimensions; XLA lowers per-axis collectives onto ICI rings for
that axis automatically — there is no comm-group object to manage, only axis
names. We keep the reference's class/API shape so fleet code ports over.

Axis order convention (outermost→innermost, matching the reference's
hybrid_group order pp→dp→sharding→mp→sp): outer axes ride DCN on multi-slice,
inner axes ride ICI — model parallel (mp) and sequence parallel (sp) want the
fastest links, so they are innermost.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..core.errors import InvalidArgumentError

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "build_mesh",
           "get_hybrid_communicate_group", "set_hybrid_communicate_group",
           "MeshDescriptor", "mesh_descriptor", "plan_resize",
           "ensure_reshardable", "ReshardError"]

# Canonical axis order. pp outermost (stages talk rarely, point-to-point),
# then dp, sharding, mp, sp innermost (tightest collectives).
_AXIS_ORDER = ("pp", "dp", "sharding", "mp", "sp")


def build_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1,
               sp: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Create the global hybrid mesh. Degrees of 1 keep their axis (size-1
    axes are free in XLA and make sharding specs uniform)."""
    devices = list(devices if devices is not None else jax.devices())
    degrees = {"pp": pp, "dp": dp, "sharding": sharding, "mp": mp, "sp": sp}
    total = int(np.prod(list(degrees.values())))
    if total != len(devices):
        raise InvalidArgumentError(
            f"Mesh degrees {degrees} require {total} devices, "
            f"have {len(devices)}")
    shape = tuple(degrees[a] for a in _AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, _AXIS_ORDER)


class ReshardError(InvalidArgumentError):
    """A checkpoint's mesh cannot be resharded to the requested world
    size/topology (elastic resize). The message teaches the fix — these
    are configuration errors, never data corruption."""


@dataclass
class MeshDescriptor:
    """JSON-serializable identity of a hybrid mesh: the axis degrees in
    canonical order plus the device count. This is what a checkpoint
    manifest records (``meta["mesh"]``) so a restore into a *different*
    world size can (a) detect that it is a resharding restore and
    (b) validate the resize is expressible before orbax touches any
    array. Pure host metadata — no device objects.
    """

    axes: Dict[str, int] = field(default_factory=dict)
    device_count: int = 1

    def degree(self, axis: str) -> int:
        return int(self.axes.get(axis, 1))

    @property
    def data_degree(self) -> int:
        """Combined degree of the data axes (dp × sharding) — the axes
        an elastic resize is allowed to scale."""
        return self.degree("dp") * self.degree("sharding")

    @property
    def model_degree(self) -> int:
        """Combined degree of the non-resizable axes (mp × pp × sp):
        resizing these would change which tensor dims are sharded, not
        just how many ways — out of scope for elastic resize."""
        return self.degree("mp") * self.degree("pp") * self.degree("sp")

    def digest(self) -> str:
        blob = json.dumps({"axes": {k: int(v) for k, v in
                                    sorted(self.axes.items())},
                           "devices": int(self.device_count)},
                          sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def as_meta(self) -> Dict[str, object]:
        """Plain-JSON form for the checkpoint manifest."""
        return {"axes": {k: int(v) for k, v in self.axes.items()},
                "device_count": int(self.device_count),
                "digest": self.digest()}

    @classmethod
    def from_meta(cls, doc) -> Optional["MeshDescriptor"]:
        """Rebuild from manifest meta; None for absent/foreign shapes
        (pre-elastic checkpoints have no mesh meta)."""
        if isinstance(doc, MeshDescriptor):
            return doc
        if not isinstance(doc, dict) or "axes" not in doc:
            return None
        return cls(axes={str(k): int(v)
                         for k, v in dict(doc["axes"]).items()},
                   device_count=int(doc.get("device_count",
                                            int(np.prod([int(v) for v in
                                                dict(doc["axes"]).values()]
                                                or [1])))))

    def __eq__(self, other):
        if not isinstance(other, MeshDescriptor):
            return NotImplemented
        return (self.device_count == other.device_count and
                {k: v for k, v in self.axes.items() if v != 1} ==
                {k: v for k, v in other.axes.items() if v != 1})


def mesh_descriptor(mesh: Mesh) -> MeshDescriptor:
    """The :class:`MeshDescriptor` of a live mesh."""
    axes = {str(name): int(size) for name, size in mesh.shape.items()}
    return MeshDescriptor(axes=axes, device_count=int(mesh.devices.size))


def plan_resize(old: MeshDescriptor, new_device_count: int
                ) -> Dict[str, int]:
    """Degrees for the resized mesh: ``build_mesh(**plan_resize(...))``.

    Elastic policy — only the *data* axes scale: ``mp``/``pp``/``sp``
    shard tensor dims and must keep their degrees (resizing them changes
    the sharded shape arithmetic, which checkpoint resharding cannot
    express without re-deciding layouts); ``dp`` and ``sharding`` absorb
    the change. Within the data axes: a degree-1 axis stays 1, and when
    both were active the ``sharding`` degree is preserved and ``dp``
    scales (ZeRO shard count is a memory contract; dp is throughput).
    Raises :class:`ReshardError` with the teaching message when the new
    world size cannot express the preserved axes.
    """
    new_device_count = int(new_device_count)
    if new_device_count < 1:
        raise ReshardError(
            f"cannot resize to a world of {new_device_count} devices")
    fixed = old.model_degree
    if new_device_count % fixed:
        raise ReshardError(
            f"world size {new_device_count} cannot carry the "
            f"checkpoint's model-parallel topology (mp={old.degree('mp')}"
            f" x pp={old.degree('pp')} x sp={old.degree('sp')} = {fixed} "
            f"does not divide {new_device_count}): elastic resize scales "
            "the data axes (dp/sharding) only — pick a world size that "
            f"is a multiple of {fixed}, or retrain/export the checkpoint "
            "at the new model-parallel degrees")
    data = new_device_count // fixed
    degrees = {"mp": old.degree("mp"), "pp": old.degree("pp"),
               "sp": old.degree("sp")}
    old_dp, old_shard = old.degree("dp"), old.degree("sharding")
    if old_shard == 1:
        degrees["dp"], degrees["sharding"] = data, 1
    elif old_dp == 1:
        degrees["dp"], degrees["sharding"] = 1, data
    else:
        if data % old_shard:
            raise ReshardError(
                f"world size {new_device_count} cannot keep the "
                f"checkpoint's ZeRO sharding degree {old_shard} "
                f"(data capacity {data} is not a multiple of it): pick "
                f"a multiple of {fixed * old_shard}, or rebuild the "
                "engine with sharding=1 to let dp absorb the resize")
        degrees["dp"], degrees["sharding"] = data // old_shard, old_shard
    return degrees


def ensure_reshardable(saved: Optional[MeshDescriptor],
                       target: MeshDescriptor) -> bool:
    """Validate that a checkpoint saved on ``saved`` can restore onto
    ``target`` (True = this IS a resharding restore; False = same mesh).
    Raises :class:`ReshardError` when the target changes a model axis —
    the one resize class the manifest-driven shard remap refuses."""
    if saved is None or saved == target:
        return False
    for axis in ("mp", "pp", "sp"):
        if saved.degree(axis) != target.degree(axis):
            raise ReshardError(
                f"checkpoint was saved on a mesh with {axis}="
                f"{saved.degree(axis)} but the restore target has "
                f"{axis}={target.degree(axis)}: elastic resize scales "
                "the data axes (dp/sharding) only. Rebuild the target "
                f"mesh with {axis}={saved.degree(axis)} (plan_resize() "
                "computes the degrees for a new world size)")
    return True


class CommunicateTopology:
    """Rank-coordinate bookkeeping over the hybrid axes (reference
    topology.py:35). Pure arithmetic — no comm objects."""

    def __init__(self, hybrid_group_names: Sequence[str],
                 dims: Sequence[int]):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(self._dims)) if self._dims else 1
        coords = np.indices(self._dims).reshape(len(self._dims), -1).T
        self._coord_to_rank = {tuple(c): r for r, c in enumerate(coords)}
        self._rank_to_coord = {r: tuple(c) for r, c in enumerate(coords)}

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **axis_coords) -> int:
        coord = tuple(axis_coords[name] for name in self._parallel_names)
        return self._coord_to_rank[coord]

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self._rank_to_coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord_to_rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Groups of ranks that communicate along ``axis_name`` (reference
        topology.py get_comm_list): one group per combination of the other
        axes' coordinates."""
        axis = self._parallel_names.index(axis_name)
        groups: Dict[Tuple[int, ...], List[int]] = {}
        for coord, rank in sorted(self._coord_to_rank.items(),
                                  key=lambda kv: kv[1]):
            others = coord[:axis] + coord[axis + 1:]
            groups.setdefault(others, []).append(rank)
        return [sorted(g) for _, g in sorted(groups.items())]


class HybridCommunicateGroup:
    """The fleet hybrid-parallel context (reference topology.py:111).

    Holds the global Mesh plus this process's logical coordinates. On TPU
    under SPMD there is one process per host controlling many devices, so
    "my rank" questions are answered per-device by XLA; the per-axis group
    objects the reference returns become axis-name handles consumed by
    shard_map/pjit.
    """

    def __init__(self, topology: CommunicateTopology,
                 mesh: Optional[Mesh] = None, rank: Optional[int] = None):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        degrees = {n: topology.get_dim(n) for n in names}
        self._mesh = mesh if mesh is not None else build_mesh(
            dp=degrees.get("data", degrees.get("dp", 1)),
            mp=degrees.get("model", degrees.get("mp", 1)),
            pp=degrees.get("pipe", degrees.get("pp", 1)),
            sharding=degrees.get("sharding", 1),
            sp=degrees.get("sep", degrees.get("sp", 1)))
        from . import env
        self._rank = rank if rank is not None else env.get_rank()
        self._coord = topology.get_coord(self._rank % topology.world_size())
        self._names = names

    # -- mesh / axis handles (TPU-native surface) ---------------------------

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def axis_name(self, logical: str) -> str:
        aliases = {"data": "dp", "model": "mp", "pipe": "pp",
                   "sharding": "sharding", "sep": "sp"}
        return aliases.get(logical, logical)

    # -- reference-compatible queries ---------------------------------------

    def _dim(self, *names) -> int:
        for n in names:
            if n in self._names:
                return self._topo.get_dim(n)
        return 1

    def _coord_of(self, *names) -> int:
        for n in names:
            if n in self._names:
                return self._coord[self._names.index(n)]
        return 0

    def get_global_rank(self) -> int:
        return self._rank

    def get_data_parallel_world_size(self) -> int:
        return self._dim("data", "dp")

    def get_data_parallel_rank(self) -> int:
        return self._coord_of("data", "dp")

    def get_model_parallel_world_size(self) -> int:
        return self._dim("model", "mp")

    def get_model_parallel_rank(self) -> int:
        return self._coord_of("model", "mp")

    def get_pipe_parallel_world_size(self) -> int:
        return self._dim("pipe", "pp")

    def get_stage_id(self) -> int:
        return self._coord_of("pipe", "pp")

    def get_sharding_parallel_world_size(self) -> int:
        return self._dim("sharding")

    def get_sharding_parallel_rank(self) -> int:
        return self._coord_of("sharding")

    def get_sep_parallel_world_size(self) -> int:
        return self._dim("sep", "sp")

    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self.get_pipe_parallel_world_size() - 1

    # group handles: on TPU these are just (mesh, axis) pairs
    def get_data_parallel_group(self):
        return _AxisGroup(self._mesh, "dp")

    def get_model_parallel_group(self):
        return _AxisGroup(self._mesh, "mp")

    def get_pipe_parallel_group(self):
        return _AxisGroup(self._mesh, "pp")

    def get_sharding_parallel_group(self):
        return _AxisGroup(self._mesh, "sharding")

    def get_sep_parallel_group(self):
        return _AxisGroup(self._mesh, "sp")

    def topology(self) -> CommunicateTopology:
        return self._topo


class _AxisGroup:
    """A (mesh, axis-name) handle standing in for the reference's
    ProcessGroup. ``nranks``/``rank`` answer locally; collective calls made
    with this group under a shard_map trace resolve to the axis name."""

    def __init__(self, mesh: Mesh, axis: str):
        self.mesh = mesh
        self.axis = axis

    @property
    def nranks(self) -> int:
        return int(self.mesh.shape[self.axis]) if self.axis in \
            self.mesh.shape else 1

    @property
    def world_size(self) -> int:
        return self.nranks

    def __repr__(self):
        return f"_AxisGroup(axis={self.axis!r}, nranks={self.nranks})"


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup) -> None:
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
