"""Hybrid-parallel topology → one nd ``jax.sharding.Mesh`` with named axes.

Analog of the reference's ``CommunicateTopology``/``HybridCommunicateGroup``
(/root/reference/python/paddle/distributed/fleet/base/topology.py:35,111),
which builds a cartesian rank mesh over (dp, pp, sharding, mp) and creates an
NCCL comm group per axis. On TPU the whole abstraction collapses onto
``jax.sharding.Mesh``: one global device mesh whose *named axes* are the
parallelism dimensions; XLA lowers per-axis collectives onto ICI rings for
that axis automatically — there is no comm-group object to manage, only axis
names. We keep the reference's class/API shape so fleet code ports over.

Axis order convention (outermost→innermost, matching the reference's
hybrid_group order pp→dp→sharding→mp→sp): outer axes ride DCN on multi-slice,
inner axes ride ICI — model parallel (mp) and sequence parallel (sp) want the
fastest links, so they are innermost.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..core.errors import InvalidArgumentError

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "build_mesh",
           "get_hybrid_communicate_group", "set_hybrid_communicate_group"]

# Canonical axis order. pp outermost (stages talk rarely, point-to-point),
# then dp, sharding, mp, sp innermost (tightest collectives).
_AXIS_ORDER = ("pp", "dp", "sharding", "mp", "sp")


def build_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1,
               sp: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Create the global hybrid mesh. Degrees of 1 keep their axis (size-1
    axes are free in XLA and make sharding specs uniform)."""
    devices = list(devices if devices is not None else jax.devices())
    degrees = {"pp": pp, "dp": dp, "sharding": sharding, "mp": mp, "sp": sp}
    total = int(np.prod(list(degrees.values())))
    if total != len(devices):
        raise InvalidArgumentError(
            f"Mesh degrees {degrees} require {total} devices, "
            f"have {len(devices)}")
    shape = tuple(degrees[a] for a in _AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, _AXIS_ORDER)


class CommunicateTopology:
    """Rank-coordinate bookkeeping over the hybrid axes (reference
    topology.py:35). Pure arithmetic — no comm objects."""

    def __init__(self, hybrid_group_names: Sequence[str],
                 dims: Sequence[int]):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(self._dims)) if self._dims else 1
        coords = np.indices(self._dims).reshape(len(self._dims), -1).T
        self._coord_to_rank = {tuple(c): r for r, c in enumerate(coords)}
        self._rank_to_coord = {r: tuple(c) for r, c in enumerate(coords)}

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **axis_coords) -> int:
        coord = tuple(axis_coords[name] for name in self._parallel_names)
        return self._coord_to_rank[coord]

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self._rank_to_coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord_to_rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Groups of ranks that communicate along ``axis_name`` (reference
        topology.py get_comm_list): one group per combination of the other
        axes' coordinates."""
        axis = self._parallel_names.index(axis_name)
        groups: Dict[Tuple[int, ...], List[int]] = {}
        for coord, rank in sorted(self._coord_to_rank.items(),
                                  key=lambda kv: kv[1]):
            others = coord[:axis] + coord[axis + 1:]
            groups.setdefault(others, []).append(rank)
        return [sorted(g) for _, g in sorted(groups.items())]


class HybridCommunicateGroup:
    """The fleet hybrid-parallel context (reference topology.py:111).

    Holds the global Mesh plus this process's logical coordinates. On TPU
    under SPMD there is one process per host controlling many devices, so
    "my rank" questions are answered per-device by XLA; the per-axis group
    objects the reference returns become axis-name handles consumed by
    shard_map/pjit.
    """

    def __init__(self, topology: CommunicateTopology,
                 mesh: Optional[Mesh] = None, rank: Optional[int] = None):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        degrees = {n: topology.get_dim(n) for n in names}
        self._mesh = mesh if mesh is not None else build_mesh(
            dp=degrees.get("data", degrees.get("dp", 1)),
            mp=degrees.get("model", degrees.get("mp", 1)),
            pp=degrees.get("pipe", degrees.get("pp", 1)),
            sharding=degrees.get("sharding", 1),
            sp=degrees.get("sep", degrees.get("sp", 1)))
        from . import env
        self._rank = rank if rank is not None else env.get_rank()
        self._coord = topology.get_coord(self._rank % topology.world_size())
        self._names = names

    # -- mesh / axis handles (TPU-native surface) ---------------------------

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def axis_name(self, logical: str) -> str:
        aliases = {"data": "dp", "model": "mp", "pipe": "pp",
                   "sharding": "sharding", "sep": "sp"}
        return aliases.get(logical, logical)

    # -- reference-compatible queries ---------------------------------------

    def _dim(self, *names) -> int:
        for n in names:
            if n in self._names:
                return self._topo.get_dim(n)
        return 1

    def _coord_of(self, *names) -> int:
        for n in names:
            if n in self._names:
                return self._coord[self._names.index(n)]
        return 0

    def get_global_rank(self) -> int:
        return self._rank

    def get_data_parallel_world_size(self) -> int:
        return self._dim("data", "dp")

    def get_data_parallel_rank(self) -> int:
        return self._coord_of("data", "dp")

    def get_model_parallel_world_size(self) -> int:
        return self._dim("model", "mp")

    def get_model_parallel_rank(self) -> int:
        return self._coord_of("model", "mp")

    def get_pipe_parallel_world_size(self) -> int:
        return self._dim("pipe", "pp")

    def get_stage_id(self) -> int:
        return self._coord_of("pipe", "pp")

    def get_sharding_parallel_world_size(self) -> int:
        return self._dim("sharding")

    def get_sharding_parallel_rank(self) -> int:
        return self._coord_of("sharding")

    def get_sep_parallel_world_size(self) -> int:
        return self._dim("sep", "sp")

    def is_first_stage(self) -> bool:
        return self.get_stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.get_stage_id() == self.get_pipe_parallel_world_size() - 1

    # group handles: on TPU these are just (mesh, axis) pairs
    def get_data_parallel_group(self):
        return _AxisGroup(self._mesh, "dp")

    def get_model_parallel_group(self):
        return _AxisGroup(self._mesh, "mp")

    def get_pipe_parallel_group(self):
        return _AxisGroup(self._mesh, "pp")

    def get_sharding_parallel_group(self):
        return _AxisGroup(self._mesh, "sharding")

    def get_sep_parallel_group(self):
        return _AxisGroup(self._mesh, "sp")

    def topology(self) -> CommunicateTopology:
        return self._topo


class _AxisGroup:
    """A (mesh, axis-name) handle standing in for the reference's
    ProcessGroup. ``nranks``/``rank`` answer locally; collective calls made
    with this group under a shard_map trace resolve to the axis name."""

    def __init__(self, mesh: Mesh, axis: str):
        self.mesh = mesh
        self.axis = axis

    @property
    def nranks(self) -> int:
        return int(self.mesh.shape[self.axis]) if self.axis in \
            self.mesh.shape else 1

    @property
    def world_size(self) -> int:
        return self.nranks

    def __repr__(self):
        return f"_AxisGroup(axis={self.axis!r}, nranks={self.nranks})"


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup) -> None:
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
