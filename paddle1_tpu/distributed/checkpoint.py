"""Sharding-aware distributed checkpointing (orbax-backed), hardened.

Reference analog: ``fluid/io.py save_persistables`` with PS-sliced vars
(each server saves its slice) and the trainer-side checkpoint of
``incubate/auto_checkpoint``. On TPU the states of interest are sharded
``jax.Array``s living across a mesh (``ParallelEngine.params`` /
``opt_state`` under dp/tp/ZeRO): gathering them to one host before
pickling (framework/io.py paddle.save) defeats ZeRO's memory story and
multiplies save time by the mesh size. This module saves each shard from
the process that owns it via orbax (OCDBT format) and restores directly
into the target sharding — the TPU-idiomatic equivalent of the
reference's per-server slice files.

Fault-tolerance contract (the auto_checkpoint role):

* **Atomic commits** — ``CheckpointManager.save`` writes into a
  ``<step>.tmp-<pid>`` sibling, stamps a ``manifest.json`` (tree
  structure + shape/dtype digest + optional host metadata), and only
  then renames into place. A write killed at ANY point leaves either a
  ``.tmp-*`` dir or a manifest-less step dir; both read as
  *uncommitted*.
* **Manifest verification** — ``restore`` checks the saved tree spec
  against the restore target before orbax touches the arrays, so a
  truncated or mismatched checkpoint fails fast instead of restoring
  garbage.
* **Fallback** — when the newest checkpoint is corrupt or partial,
  ``restore`` walks backward to the newest one that verifies and loads.
* **GC hygiene** — ``latest_step``/``_gc`` parse step names defensively
  (non-numeric entries skipped, never crashed on), count only committed
  checkpoints toward retention (a partial dir can no longer push a good
  checkpoint out of the window), and sweep uncommitted debris.

``paddle.save``/``paddle.load`` remain the right tool for single-host
state dicts; use this for engine-scale state.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax

from ..core.errors import EnforceNotMet

__all__ = ["save_sharded", "load_sharded", "latest_step",
           "committed_steps", "CheckpointCorruptError", "CheckpointManager",
           "MANIFEST_NAME", "write_manifest", "read_manifest",
           "verify_manifest", "tree_mesh_descriptor", "manifest_mesh",
           "read_sidecar"]

MANIFEST_NAME = "manifest.json"


class CheckpointCorruptError(EnforceNotMet, IOError):
    """No checkpoint under the directory survived verification."""


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _abstract(tree):
    """Shape/dtype/sharding skeleton of a live state tree — the restore
    target orbax needs to place shards directly on the right devices."""
    def one(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x
    return jax.tree_util.tree_map(one, tree)


def save_sharded(path: str, state: Dict[str, Any], *, force: bool = True):
    """Save a pytree of (possibly sharded) jax.Arrays; every process
    writes only the shards it owns."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def tree_mesh_descriptor(tree):
    """MeshDescriptor of the mesh the tree's arrays live on (first
    mesh-sharded leaf wins — one engine state tree has one mesh), or
    None for host-only/abstract-unsharded trees."""
    from .topology import mesh_descriptor
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "devices", None) is not None:
            return mesh_descriptor(mesh)
    return None


def manifest_mesh(path: str):
    """The MeshDescriptor stamped into a checkpoint's manifest meta, or
    None (pre-elastic checkpoint / no manifest)."""
    from .topology import MeshDescriptor
    doc = read_manifest(path)
    if doc is None:
        return None
    return MeshDescriptor.from_meta((doc.get("meta") or {}).get("mesh"))


# "not provided" sentinel for load_sharded's saved_mesh: None is a
# meaningful value (known pre-elastic checkpoint — skip the manifest
# re-read a caller who already parsed it would otherwise pay)
_MESH_UNKNOWN = object()


def load_sharded(path: str, target: Dict[str, Any], *,
                 saved_mesh=_MESH_UNKNOWN):
    """Restore into the shardings of ``target`` (a live or abstract state
    tree). Returns the restored pytree.

    Resharding load path: when the checkpoint was written on a
    *different* mesh than ``target``'s arrays live on (``saved_mesh``,
    normally read from the manifest — :func:`manifest_mesh` — by the
    caller; read from the manifest beside ``path`` here when omitted),
    the old-shard → new-shard slice remap is validated first
    (:func:`~.topology.ensure_reshardable`: only the data axes
    dp/sharding may change degree) and then performed by orbax against
    the target shardings directly — each process reads exactly the byte
    ranges its new shards cover, so a grown or shrunk world never
    materializes the full tree on one host.
    """
    path = os.path.abspath(path)
    if saved_mesh is _MESH_UNKNOWN:
        saved_mesh = manifest_mesh(path)
    tgt_mesh = tree_mesh_descriptor(target)
    if tgt_mesh is not None:
        from .topology import ensure_reshardable
        if ensure_reshardable(saved_mesh, tgt_mesh):
            warnings.warn(
                f"resharding restore: checkpoint {os.path.basename(path)} "
                f"was saved on {saved_mesh.device_count} device(s) "
                f"{dict(saved_mesh.axes)}, restoring onto "
                f"{tgt_mesh.device_count} device(s) {dict(tgt_mesh.axes)}")
    return _checkpointer().restore(path, _abstract(target))


# -- manifests ---------------------------------------------------------------

def _tree_spec(state) -> List[Tuple[str, List[int], str]]:
    """(path, shape, dtype) per leaf — the structural identity of a
    checkpoint, cheap to compute and to compare."""
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    spec = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        shape = [int(s) for s in getattr(leaf, "shape", ())]
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        spec.append((key, shape, dtype))
    return spec


def _spec_digest(spec) -> str:
    blob = json.dumps(spec, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _json_safe_meta(obj, keypath="meta"):
    """Coerce checkpoint meta to plain JSON types, naming the offending
    key on anything that can't ride the manifest. Numpy scalars (an
    easy accident in sampler/loader state: seeds, cursors) are narrowed
    to their Python equivalents instead of failing mid-write — a
    TypeError out of ``json.dump`` half-way through the manifest names
    neither the key nor the caller."""
    import numpy as _np
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    # typed host metadata the manifest knows how to flatten: the mesh/
    # topology descriptor rides every elastic checkpoint (resharding
    # restores need it to validate the resize before touching arrays)
    from .topology import MeshDescriptor
    if isinstance(obj, MeshDescriptor):
        return obj.as_meta()
    if isinstance(obj, _np.bool_):
        return bool(obj)
    if isinstance(obj, _np.integer):
        return int(obj)
    if isinstance(obj, _np.floating):
        return float(obj)
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                raise CheckpointCorruptError(
                    f"checkpoint meta key {keypath}[{k!r}] is not a "
                    "string — manifest meta must be JSON-serializable")
            out[k] = _json_safe_meta(v, f"{keypath}.{k}")
        return out
    if isinstance(obj, (list, tuple)):
        return [_json_safe_meta(v, f"{keypath}[{i}]")
                for i, v in enumerate(obj)]
    raise CheckpointCorruptError(
        f"checkpoint meta value at {keypath} "
        f"({type(obj).__name__}) is not JSON-serializable — manifest "
        "meta carries small host state only (steps, seeds, cursors)")


def _write_sidecars(path: str,
                    sidecars: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Write each sidecar as ``sidecar-<name>.npz`` inside the (still
    uncommitted) step dir and return the manifest entry mapping name →
    file + sha256. The manifest rename is what commits them — a reader
    never sees a sidecar without its digest."""
    import numpy as _np
    info: Dict[str, Any] = {}
    for name, arrays in sidecars.items():
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", name):
            raise ValueError(
                f"sidecar name {name!r} must be a plain identifier "
                "(it becomes a filename inside the checkpoint)")
        fname = f"sidecar-{name}.npz"
        fp = os.path.join(path, fname)
        with open(fp, "wb") as f:
            _np.savez(f, **{k: _np.asarray(v)
                            for k, v in arrays.items()})
            f.flush()
            os.fsync(f.fileno())
        with open(fp, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        info[name] = {"file": fname, "sha256": digest}
    return info


def read_sidecar(path: str, name: str) -> Dict[str, Any]:
    """Load + digest-verify one sidecar of a COMMITTED checkpoint dir.
    Raises :class:`CheckpointCorruptError` when the manifest has no
    such sidecar, the file is missing, or its sha256 no longer matches
    the one stamped at commit time."""
    import numpy as _np
    doc = read_manifest(path)
    if doc is None:
        raise CheckpointCorruptError(
            f"checkpoint {path} has no readable manifest — not a "
            "committed checkpoint")
    info = (doc.get("meta") or {}).get("sidecars", {}).get(name)
    if info is None:
        raise CheckpointCorruptError(
            f"checkpoint {path} has no sidecar {name!r} "
            f"(manifest lists {sorted((doc.get('meta') or {}).get('sidecars', {}))})")
    fp = os.path.join(path, info["file"])
    try:
        with open(fp, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointCorruptError(
            f"sidecar {name!r} of checkpoint {path} is unreadable: "
            f"{e}") from e
    digest = hashlib.sha256(blob).hexdigest()
    if digest != info["sha256"]:
        raise CheckpointCorruptError(
            f"sidecar {name!r} of checkpoint {path} failed digest "
            f"verification (manifest {info['sha256'][:12]}…, file "
            f"{digest[:12]}…) — treat this checkpoint as corrupt")
    import io as _io
    with _np.load(_io.BytesIO(blob), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def write_manifest(path: str, state, meta: Optional[Dict[str, Any]] = None):
    """Stamp ``manifest.json`` into a checkpoint dir: the commit marker
    plus the tree spec ``restore`` verifies against its target."""
    spec = _tree_spec(state)
    doc = {"version": 1, "tree": spec, "digest": _spec_digest(spec),
           "meta": _json_safe_meta(meta or {})}
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The manifest of a checkpoint dir, or None when absent/unreadable
    (both mean: not a committed checkpoint)."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "tree" not in doc:
            return None
        return doc
    except (OSError, ValueError):
        return None


def verify_manifest(path: str, target) -> Dict[str, Any]:
    """Check a checkpoint's manifest against the restore target's tree
    spec; returns the manifest. Raises CheckpointCorruptError on a
    missing manifest or a structure/shape/dtype mismatch."""
    doc = read_manifest(path)
    if doc is None:
        raise CheckpointCorruptError(
            f"checkpoint {path} has no readable manifest "
            "(partial write, or pre-manifest checkpoint)")
    saved = [[k, [int(x) for x in s], d] for k, s, d in doc["tree"]]
    want = [[k, list(s), d] for k, s, d in _tree_spec(target)]
    if saved != want:
        diff = next(((a, b) for a, b in zip(saved, want) if a != b),
                    ("<leaf-count>", (len(saved), len(want))))
        raise CheckpointCorruptError(
            f"checkpoint {path} does not match the restore target "
            f"({len(saved)} vs {len(want)} leaves; first difference: "
            f"saved={diff[0]} target={diff[1]})")
    return doc


# -- step-dir bookkeeping ----------------------------------------------------

_STEP_RE = re.compile(r"\d+$")


def _step_of(name: str) -> Optional[int]:
    """Parse a step-dir name; None for anything non-numeric (including
    unicode digits that ``str.isdigit`` accepts but ``int`` rejects,
    tmp dirs, and stray files)."""
    return int(name) if _STEP_RE.fullmatch(name) else None


def committed_steps(directory: str) -> List[int]:
    """Ascending steps of COMMITTED checkpoints (numeric dir name +
    readable manifest). Partial writes, tmp dirs and foreign files are
    skipped, never crashed on."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for d in os.listdir(directory):
        s = _step_of(d)
        if s is None:
            continue
        p = os.path.join(directory, d)
        if os.path.isdir(p) and read_manifest(p) is not None:
            steps.append(s)
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Largest committed step under ``directory``, or None."""
    steps = committed_steps(directory)
    return steps[-1] if steps else None


class CheckpointManager:
    """Step-numbered checkpoints with retention (reference
    auto_checkpoint epoch-range semantics at engine scale), atomic
    commits, manifest verification and corrupt-checkpoint fallback."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        if int(max_to_keep) < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep} "
                f"(steps[:-0] would silently disable retention)")
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def save(self, step: int, state: Dict[str, Any],
             meta: Optional[Dict[str, Any]] = None,
             sidecars: Optional[Dict[str, Dict[str, Any]]] = None):
        """Atomically commit ``state`` as checkpoint ``step``.

        Write order: orbax save into ``<step>.tmp-<pid>`` → sidecar npz
        files written beside the arrays → manifest stamped inside it
        (the commit marker, carrying each sidecar's sha256) → rename
        over the final path. A crash (or an injected ``ckpt_fail``)
        before the rename leaves only uncommitted debris that
        restore/GC ignore/sweep.
        ``meta`` (small, JSON-serializable — step counters, RNG state)
        rides in the manifest, not in orbax arrays. ``sidecars`` is for
        HOST state too big/ragged for the manifest and outside the
        device tree (the embedding engine's admission ledger, a host
        SparseTable tier): ``{name: {key: array-or-scalar}}``, each
        saved as one npz inside the step dir — committed by the same
        rename, digest-verified by :meth:`read_sidecar`.
        """
        final = self._step_dir(step)
        multi = jax.process_count() > 1
        # multi-host: every process must feed orbax the SAME path (each
        # writes only the shards it owns); single-host, a pid suffix
        # keeps concurrent managers from clobbering each other's tmp
        tmp = f"{final}.tmp" if multi else f"{final}.tmp-{os.getpid()}"
        if multi:
            # the orbax save is itself a collective (every process
            # writes its shards against the same path): journal it
            from ..core import collective_sanitizer
            collective_sanitizer.note_collective(
                "ckpt_save_sharded", (),
                site=f"checkpoint.save:{int(step)}")
        save_sharded(tmp, state)
        commit_err: Optional[Exception] = None
        # one committer, everyone learns the outcome: the guarded
        # commit below is paired with the broadcast_one_to_all outcome
        # barrier — the pairing the commit-protocol lint pass enforces
        if jax.process_index() == 0:  # commit-protocol: ckpt-commit
            try:
                from ..core import chaos
                chaos.check_checkpoint_write()  # injected mid-write
                # failure: arrays on disk, no manifest, no rename —
                # an uncommitted partial
                if sidecars:
                    meta = dict(meta or {})
                    meta["sidecars"] = _write_sidecars(tmp, sidecars)
                write_manifest(tmp, state, meta=meta)
                if os.path.isdir(final):
                    # re-saving an existing step (rollback-and-replay):
                    # move the old commit ASIDE first, swap the new one
                    # in, then delete — a crash mid-swap leaves either
                    # the old commit or the new one plus uncommitted
                    # debris, never neither
                    old = f"{final}.old-{os.getpid()}"
                    os.replace(final, old)
                    os.replace(tmp, final)
                    shutil.rmtree(old, ignore_errors=True)
                else:
                    os.replace(tmp, final)
                self._gc()
            except Exception as e:
                # do NOT raise before the collective below: peers must
                # learn the outcome or they'd block at the next barrier
                # (and a caller-side retry would re-enter the orbax
                # collective with mismatched participants)
                commit_err = e
        if multi:
            # outcome broadcast doubles as the commit barrier: every
            # process raises together on failure, so a retry re-enters
            # the collective save in lockstep — and no process reports
            # success for a checkpoint that was never committed
            import numpy as _np
            from jax.experimental import multihost_utils

            # the commit barrier is part of the rank's collective
            # schedule: journal it so a rank-conditional retry that
            # re-enters it alone (the PR 2 shape) diverges loudly
            # under the collective-schedule sanitizer
            from ..core import collective_sanitizer
            collective_sanitizer.note_collective(
                "ckpt_outcome_broadcast", (),
                site=f"checkpoint.save:{int(step)}")
            ok = multihost_utils.broadcast_one_to_all(
                _np.asarray(commit_err is None))
            if not bool(ok):
                if commit_err is not None:
                    raise commit_err
                raise IOError(
                    f"checkpoint {step} commit failed on process 0")
        elif commit_err is not None:
            raise commit_err
        return final

    def restore(self, target: Dict[str, Any],
                step: Optional[int] = None):
        """Restore the newest checkpoint that verifies (or exactly
        ``step`` when given), falling back past corrupt/partial ones.
        Returns ``(restored_tree, step)``."""
        from .topology import MeshDescriptor, ReshardError

        def _load(path):
            # reuse the just-verified manifest's mesh — restore is the
            # recovery hot path, no point parsing manifest.json twice
            doc = verify_manifest(path, target)
            mesh = MeshDescriptor.from_meta(
                (doc.get("meta") or {}).get("mesh"))
            return load_sharded(path, target, saved_mesh=mesh)

        if step is not None:
            path = self._step_dir(step)
            return _load(path), int(step)
        candidates = committed_steps(self.directory)
        if not candidates:
            raise FileNotFoundError(
                f"no committed checkpoints under {self.directory}")
        errors = []
        for s in reversed(candidates):
            path = self._step_dir(s)
            try:
                return _load(path), s
            except ReshardError:
                # a configuration error, not corruption: every older
                # checkpoint of this run shares the mesh, so falling
                # back would just repeat the failure — surface the
                # teaching message immediately
                raise
            except Exception as e:
                # corrupt / truncated / mismatched — fall back to the
                # previous checkpoint rather than dying on the newest
                errors.append((s, e))
                warnings.warn(
                    f"checkpoint step {s} failed to restore "
                    f"({type(e).__name__}: {e}); falling back")
        raise CheckpointCorruptError(
            f"every checkpoint under {self.directory} failed to "
            f"restore: {[(s, str(e)) for s, e in errors]}")

    def read_meta(self, step: Optional[int] = None) -> \
            Optional[Dict[str, Any]]:
        """Host metadata stamped into a checkpoint's manifest."""
        step = self.latest_step() if step is None else int(step)
        if step is None:
            return None
        doc = read_manifest(self._step_dir(step))
        return None if doc is None else doc.get("meta", {})

    def read_sidecar(self, name: str,
                     step: Optional[int] = None) -> Dict[str, Any]:
        """Digest-verified sidecar arrays of checkpoint ``step`` (the
        newest committed one when omitted)."""
        step = self.latest_step() if step is None else int(step)
        if step is None:
            raise CheckpointCorruptError(
                f"no committed checkpoints under {self.directory}")
        return read_sidecar(self._step_dir(step), name)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def all_steps(self) -> List[int]:
        return committed_steps(self.directory)

    # debris younger than this may belong to a live writer in another
    # process — leave it for a later sweep
    _DEBRIS_MIN_AGE_S = 300.0

    def _gc(self):
        """Retention over COMMITTED checkpoints only, plus a sweep of
        uncommitted debris (tmp/old dirs from killed writes; numeric
        dirs that never got their manifest). Our own just-failed tmp is
        reaped immediately; anything that could be ANOTHER process's
        in-flight write is only reaped once it has gone stale."""
        import time
        committed = set(committed_steps(self.directory))
        for s in sorted(committed)[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
        own = f"-{os.getpid()}"
        now = time.time()
        for d in os.listdir(self.directory):
            p = os.path.join(self.directory, d)
            if not os.path.isdir(p):
                continue
            mine = d.endswith(own)
            try:
                stale = now - os.path.getmtime(p) > self._DEBRIS_MIN_AGE_S
            except OSError:
                continue  # vanished under us (concurrent GC/commit)
            s = _step_of(d)
            if s is not None and s not in committed:
                # numeric but manifest-less: under the new protocol this
                # can only be a LEGACY (pre-manifest) checkpoint or a
                # foreign dir — the commit path never renames anything
                # numeric into place without its manifest. Deleting
                # could destroy a prior run's only valid checkpoints on
                # upgrade, so PRESERVE it; it is merely excluded from
                # latest_step/retention/restore (uncommittable).
                continue
            elif s is None and (".tmp" in d or ".old-" in d):
                # possibly a peer process's in-flight write: reap only
                # our own, or clearly abandoned (stale) debris
                if mine or stale:
                    shutil.rmtree(p, ignore_errors=True)
