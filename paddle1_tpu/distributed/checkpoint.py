"""Sharding-aware distributed checkpointing (orbax-backed).

Reference analog: ``fluid/io.py save_persistables`` with PS-sliced vars
(each server saves its slice) and the trainer-side checkpoint of
``incubate/auto_checkpoint``. On TPU the states of interest are sharded
``jax.Array``s living across a mesh (``ParallelEngine.params`` /
``opt_state`` under dp/tp/ZeRO): gathering them to one host before
pickling (framework/io.py paddle.save) defeats ZeRO's memory story and
multiplies save time by the mesh size. This module saves each shard from
the process that owns it via orbax (OCDBT format) and restores directly
into the target sharding — the TPU-idiomatic equivalent of the
reference's per-server slice files.

``paddle.save``/``paddle.load`` remain the right tool for single-host
state dicts; use this for engine-scale state.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

__all__ = ["save_sharded", "load_sharded", "latest_step",
           "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _abstract(tree):
    """Shape/dtype/sharding skeleton of a live state tree — the restore
    target orbax needs to place shards directly on the right devices."""
    def one(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x
    return jax.tree_util.tree_map(one, tree)


def save_sharded(path: str, state: Dict[str, Any], *, force: bool = True):
    """Save a pytree of (possibly sharded) jax.Arrays; every process
    writes only the shards it owns."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def load_sharded(path: str, target: Dict[str, Any]):
    """Restore into the shardings of ``target`` (a live or abstract state
    tree). Returns the restored pytree."""
    path = os.path.abspath(path)
    return _checkpointer().restore(path, _abstract(target))


def latest_step(directory: str) -> Optional[int]:
    """Largest numeric subdirectory (step) under ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    return max(steps) if steps else None


class CheckpointManager:
    """Step-numbered checkpoints with retention (reference
    auto_checkpoint epoch-range semantics at engine scale)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        if int(max_to_keep) < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep} "
                f"(steps[:-0] would silently disable retention)")
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def save(self, step: int, state: Dict[str, Any]):
        save_sharded(self._step_dir(step), state)
        self._gc()
        return self._step_dir(step)

    def restore(self, target: Dict[str, Any], step: Optional[int] = None):
        step = self.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        return load_sharded(self._step_dir(step), target), step

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def _gc(self):
        import shutil
        steps = sorted(int(d) for d in os.listdir(self.directory)
                       if d.isdigit())
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
