"""In-graph pipeline parallelism over the ``pp`` mesh axis.

The reference runs pipelines with a C++ interpreter thread per stage
(SectionWorker 1F1B, framework/section_worker.cc:143-181) and NCCL P2P ops
at the cuts. Under XLA there is no interpreter to schedule — the pipeline
must live INSIDE the compiled program (SURVEY §7 hard part b). This module
implements the idiomatic TPU form:

* stage weights are stacked on a leading axis sharded over ``pp``;
* one ``lax.scan`` over clock ticks runs every stage in parallel (SPMD),
  with ``lax.ppermute`` rotating activations one ICI neighbor per tick —
  the fill/steady/drain schedule (GPipe-style);
* ``jax.grad`` through the scan yields the backward pipeline for free
  (reverse ticks, reversed ppermute); per-tick rematerialisation keeps
  activation memory at one microbatch per stage, and XLA's latency-hiding
  scheduler overlaps the ppermute with the next tick's compute — which is
  the property 1F1B hand-scheduling buys on GPUs.

Shape contract: microbatches [n_micro, micro_bs, ...]; every stage maps
[micro_bs, d] → [micro_bs, d] (homogeneous stages — stack your transformer
blocks; first/last stage embeddings/heads live outside the pipelined body).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """List of per-stage pytrees → one pytree with a leading stage axis
    (shard it over 'pp')."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def pipeline_apply(stage_fn: Callable, stacked_params, micro_inputs,
                   axis_name: str = "pp", micro_aux=None):
    """Run the pipelined forward inside shard_map.

    stage_fn(params_one_stage, x) -> y, pure, same shape in/out — or
    stage_fn(params, x, aux) when ``micro_aux`` is given.
    stacked_params: pytree with leading stage axis, arriving SHARDED over
    ``axis_name`` (leading dim 1 per device inside shard_map).
    micro_inputs: [n_micro, micro_bs, ...] replicated across pp.
    micro_aux: optional pytree of [n_micro, ...] per-microbatch side
    inputs (e.g. attention masks) consumed by EVERY stage; stage s at
    tick t reads the aux of the microbatch it is processing (t - s).

    Returns [n_micro, micro_bs, ...]: outputs of the LAST stage in
    microbatch order (replicated via final broadcast).
    """
    n_stages = lax.axis_size(axis_name)
    stage_id = lax.axis_index(axis_name)
    n_micro = micro_inputs.shape[0]
    leading = {x.shape[0] for x in
               jax.tree_util.tree_leaves(stacked_params)}
    if leading != {1}:
        raise ValueError(
            f"pipeline_apply: stacked stage count must equal the "
            f"'{axis_name}' mesh axis size (got local leading dims "
            f"{sorted(leading)}; shard the stage axis over '{axis_name}')")
    local_params = jax.tree_util.tree_map(lambda x: x[0], stacked_params)
    ticks = n_micro + n_stages - 1
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t (zeros past the fill phase)
        fresh = jnp.where(t < n_micro,
                          micro_inputs[jnp.minimum(t, n_micro - 1)],
                          jnp.zeros_like(micro_inputs[0]))
        x = jnp.where(stage_id == 0, fresh, buf)
        if micro_aux is not None:
            mb_idx = jnp.clip(t - stage_id, 0, n_micro - 1)
            aux = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, mb_idx, 0,
                                                   keepdims=False),
                micro_aux)
            y = stage_fn(local_params, x, aux)
        else:
            y = stage_fn(local_params, x)
        # last stage emits microbatch t-(n_stages-1) at tick t
        out_idx = t - (n_stages - 1)
        is_out = (out_idx >= 0) & (stage_id == n_stages - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(is_out, y, lax.dynamic_index_in_dim(
                outputs, jnp.maximum(out_idx, 0), 0, keepdims=False)),
            jnp.maximum(out_idx, 0), 0)
        # rotate activations one neighbor down the ring
        buf = lax.ppermute(y, axis_name, perm_fwd)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(micro_inputs[0])
    outs0 = jnp.zeros_like(micro_inputs)
    _vary = getattr(lax, "pcast", None)
    if _vary is not None:
        buf0 = _vary(buf0, (axis_name,), to="varying")
        outs0 = _vary(outs0, (axis_name,), to="varying")
    else:  # pragma: no cover - older jax
        buf0 = lax.pvary(buf0, (axis_name,))
        outs0 = lax.pvary(outs0, (axis_name,))
    (buf, outputs), _ = lax.scan(
        jax.checkpoint(tick), (buf0, outs0), jnp.arange(ticks))
    # broadcast last stage's outputs to every pp rank (so the loss is
    # computable everywhere under SPMD)
    mask = (stage_id == n_stages - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)
