"""Compiled hybrid-parallel training engine.

This is the TPU-native replacement for the whole meta-optimizer /
ParallelExecutor stack of the reference (SURVEY §2.3, §3.1): where the
reference rewrites a ProgramDesc per strategy (insert c_allreduce for DP,
split programs for PP, prune for ZeRO — fleet/base/fleet_base.py:1212
minimize → StrategyCompiler) and interprets it op-by-op, we compose ONE pure
train-step function (loss → grad → optimizer update) and jit it over the
hybrid ``Mesh`` with `NamedSharding` annotations; GSPMD inserts every
collective (grad psum for DP, Megatron f/g for TP, reduce-scatter/all-gather
for ZeRO) and the latency-hiding scheduler overlaps them with compute — the
Reducer-overlap problem (SURVEY §7 hard part a) solved by the compiler.

Usage::

    engine = ParallelEngine(model, opt, loss_fn, strategy=dist_strategy)
    for batch in loader:
        loss = engine.step(batch)      # one fused XLA executable
    engine.sync_model()                # write params back into the Layer

Multi-step (device-resident) training: every ``step`` call pays one
dispatch through the host→device tunnel (~70 ms through the axon tunnel
per the bench honesty contract), and every eager ``float(loss)`` pays a
device→host readback. ``step_many`` amortizes both: k optimizer steps
run inside ONE jitted executable via ``lax.scan`` (one dispatch, one
donation cycle), losses come back as a single lazy ``LossFuture`` over
the ``[k]`` device array — zero intermediate readbacks::

    for losses in engine.step_stream(loader):  # k steps per dispatch,
        pass                                   # k = train_steps_per_sync
    engine.sync_model()                        # drains in-flight work first
"""

from __future__ import annotations

import collections
import functools
import time
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import flags as core_flags
from ..core import async_loss
from ..core import jit_sanitizer
from ..core.async_loss import LossFuture, StepFuture
from ..obs import costmodel as obs_costmodel
from ..obs import flight as obs_flight
from ..obs import hbm as obs_hbm
from ..obs import trace as obs_trace
from ..core.generator import next_key, rng_scope
from ..core.tensor import Tensor
from ..autograd import engine as autograd_engine
from ..nn.layer_base import Layer
from .sharding_specs import (data_partition_spec, param_partition_specs,
                             zero_shard_spec)
from .topology import build_mesh

__all__ = ["ParallelEngine", "make_train_step"]


def _obs_step_registry():
    """The process registry iff per-step instrumentation is on
    (obs_metrics flag) — one flag read on the hot path, None otherwise
    (the bench --obs disabled-cost contract)."""
    from ..obs import registry as obs_registry
    return obs_registry.step_registry()


# process-level throughput state behind the train_samples_per_s /
# train_steps_per_readback gauges: one gauge family per process, so the
# state is process-global too — two engines in one process (train +
# eval, GAN pairs) contribute to ONE aggregate instead of clobbering
# each other with per-engine numbers against a process-wide readback
# counter
_obs_thru = {"rb_base": None, "last_t": None, "rate": None,
             "mfu": None, "bw": None, "peaks": None}


def _obs_peaks():
    """(peak_flops, peak_hbm_bw) for this process's device — cached
    (the cost-model denominators; shared with bench.py's analytic
    MFU via obs.costmodel's tables)."""
    st = _obs_thru
    if st["peaks"] is None:
        dev = jax.devices()[0]
        st["peaks"] = (obs_costmodel.device_peak_flops(dev),
                       obs_costmodel.device_peak_hbm_bw(dev))
    return st["peaks"]


def _obs_note_steps(m, k: int, rows: int, t_now: float,
                    cost=None) -> None:
    """Feed the throughput gauges after an instrumented dispatch:
    samples/s as an EWMA over wall time between dispatches,
    steps-per-readback (how well the lazy-loss window amortizes the
    host round trip — the step_many story in one number), and — when
    the jit-site cost is known (ISSUE 13) — the per-step cost gauges
    plus MFU / HBM-bandwidth utilization against the device peaks.
    Wall-clock MFU is trustworthy once the in-flight window saturates
    (dispatch run-ahead can inflate the first instants)."""
    st = _obs_thru
    if st["rb_base"] is None:
        st["rb_base"] = async_loss.readback_count()
    c = m.counter("train_steps_total")
    c.inc(k)
    last, st["last_t"] = st["last_t"], t_now
    dt = (t_now - last) if (last is not None and t_now > last) else None
    if dt is not None:
        inst = (k * rows) / dt
        st["rate"] = inst if st["rate"] is None else \
            0.8 * st["rate"] + 0.2 * inst
        m.gauge("train_samples_per_s").set(st["rate"])
    rb = async_loss.readback_count() - st["rb_base"]
    total = c.value
    m.gauge("train_steps_per_readback").set(
        total / rb if rb > 0 else float(total))
    mfu = None
    if cost is not None and cost.flops:
        m.gauge("train_step_flops").set(cost.flops)
        m.gauge("train_step_bytes").set(cost.bytes_accessed)
        m.gauge("train_cost_exact").set(1.0 if cost.exact else 0.0)
        if dt is not None:
            peak_f, peak_bw = _obs_peaks()
            mfu_i = (k * cost.flops / dt) / peak_f
            st["mfu"] = mfu_i if st["mfu"] is None else \
                0.8 * st["mfu"] + 0.2 * mfu_i
            m.gauge("train_mfu").set(st["mfu"])
            mfu = st["mfu"]
            bw_i = (k * cost.bytes_accessed / dt) / peak_bw
            st["bw"] = bw_i if st["bw"] is None else \
                0.8 * st["bw"] + 0.2 * bw_i
            m.gauge("train_hbm_bw_util").set(st["bw"])
    # flight ring first: if the leak detector below raises, the crash
    # dump still holds this step
    fr = obs_flight.recorder()
    if fr is not None:
        fr.note_step(step=total,
                     samples_per_s=round(st["rate"] or 0.0, 2),
                     mfu=(round(mfu, 4) if mfu is not None else None),
                     hbm_bytes=obs_hbm.last_total())
    # HBM census: per-subsystem registered bytes, sampled (at most
    # once per interval — the walk is O(registered leaves)) and fed
    # into the flag-gated monotone-growth leak detector
    obs_hbm.step_sample(m)


_readback_obs_installed = False


def _ensure_readback_observer():
    """Route LossFuture materialization durations into the process
    registry's train_readback_seconds histogram (idempotent; installed
    the first time an instrumented step runs, so uninstrumented
    processes never pay the per-fetch perf_counter)."""
    global _readback_obs_installed
    if _readback_obs_installed:
        return
    _readback_obs_installed = True
    from ..obs import registry as obs_registry

    def observe(dt: float) -> None:
        if obs_registry.metrics_on():
            obs_registry.process_registry().histogram(
                "train_readback_seconds").observe(dt)

    async_loss.set_readback_observer(observe)


def _as_arrays(batch):
    """Tensor/np leaves → jax arrays, preserving tree structure."""
    if isinstance(batch, Tensor):
        return batch.data
    if isinstance(batch, (list, tuple)):
        return type(batch)(_as_arrays(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _as_arrays(v) for k, v in batch.items()}
    return jnp.asarray(batch)


def make_train_step(layer: Layer, optimizer, loss_fn: Callable,
                    grad_accum: int = 1,
                    clip_global_norm: Optional[float] = None,
                    amp_dtype: Optional[str] = None,
                    recompute: bool = False,
                    grad_shardings=None,
                    check_finite: bool = False):
    """Build the pure train-step: (params, opt_state, batch, key, lr) →
    (loss, params, opt_state).

    ``check_finite=True`` folds device-side bad-step detection into the
    same executable: a non-finite loss or gradient (NaN batch, amp
    overflow) flips an on-device flag, the optimizer update is *skipped*
    via a ``where``-select back to the incoming params/opt_state (so a
    poisoned batch can never corrupt the model, even while the host is
    still dispatching ahead of the readback), and the step returns a
    packed ``[loss, notfinite]`` pair instead of the bare loss — the
    flag rides the loss's own readback, costing zero extra transfers.

    ``loss_fn(model, batch)`` runs the model's eager code; under trace the
    tape is off and jax.grad differentiates the pure function — eager and
    compiled mode share one autograd (the dygraph/static parity the
    reference maintains with two separate engines, backward.py:1363 vs
    basic_engine.cc).
    """

    # Functionalized batch-norm running stats (ADVICE r5 medium): the
    # momentum per captured buffer, recorded at trace time — a plain
    # Python side channel, like the trace counters. The traced batch
    # stats ride pure_loss's aux output; train_step blends them with
    # the incoming buffer values and writes the result into the step's
    # OUTPUT params, so compiled training keeps running stats exactly
    # like eager training and sync_model/checkpoints see them — no
    # extra outputs, no extra transfers.
    stat_momentum: Dict[str, float] = {}

    def pure_loss(params, batch, key):
        if amp_dtype is not None:
            # bf16 autocast: compute params in bf16, masters stay f32 in
            # the optimizer (reference pure-fp16 mode, fp16_utils.py:322)
            cdt = jnp.dtype(amp_dtype)
            params = {k: (v.astype(cdt)
                          if jnp.issubdtype(v.dtype, jnp.floating) else v)
                      for k, v in params.items()}
            # feeds too (reference pure-fp16 casts the feed vars as well,
            # fp16_utils.py cast_model_to_fp16): f32 images x bf16 conv
            # weights is a dtype error on TPU
            batch = jax.tree_util.tree_map(
                lambda a: a.astype(cdt)
                if (hasattr(a, "dtype")
                    and jnp.issubdtype(a.dtype, jnp.floating)) else a,
                batch)
        from ..nn.functional import norm as fnorm
        with autograd_engine.no_grad(), rng_scope(key):
            with layer.load_functional_state(params):
                with fnorm.collect_stat_updates() as stat_updates:
                    out = loss_fn(layer, batch)
        out = out.data if isinstance(out, Tensor) else out
        aux = {}
        if stat_updates:
            # map each captured OLD buffer array back to its params key
            # by identity (load_functional_state swapped exactly these
            # arrays in), and emit the raw batch stats as aux — the
            # old/new blend happens in train_step, where composing
            # multiple micro-steps is well-defined
            ids = {id(v): k for k, v in params.items()}
            for u in stat_updates:
                for old, stat in ((u.old_mean, u.mean),
                                  (u.old_var, u.var)):
                    name = ids.get(id(old))
                    if name is None:
                        continue  # buffer not threaded through params
                    stat_momentum[name] = float(u.momentum)
                    aux[name] = stat.astype(jnp.float32)
        return out.astype(jnp.float32), aux

    if recompute:
        # Rematerialisation must be per-BLOCK to cut peak memory
        # (checkpointing the whole loss would re-run the forward without
        # reducing the residual set). Flip the recompute switch on every
        # block-structured sublayer that supports it.
        from ..nn.layer_transformer import TransformerEncoder
        flipped = 0
        for sub in layer.sublayers(include_self=True):
            if isinstance(sub, TransformerEncoder):
                sub.enable_recompute = True
                flipped += 1
        if not flipped:
            import warnings
            warnings.warn(
                "recompute=True: no recompute-capable blocks found "
                "(TransformerEncoder); wrap your own blocks with "
                "fleet.utils.recompute for per-segment remat")

    def train_step(params, opt_state, batch, key, lr):
        if grad_accum > 1:
            # micro-batch scan: batch leaves are [accum, micro, ...]
            def micro(carry, xs):
                g_acc, i = carry
                mb, k = xs
                (l, aux), g = jax.value_and_grad(
                    pure_loss, has_aux=True)(params, mb, k)
                g_acc = jax.tree_util.tree_map(lambda a, b: a + b, g_acc, g)
                return (g_acc, i + 1), (l, aux)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            keys = jax.random.split(key, grad_accum)
            (grads, _), (losses, aux) = jax.lax.scan(micro, (zeros, 0),
                                                     (batch, keys))
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, grads)
            loss = jnp.mean(losses)
        else:
            (loss, aux), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(params, batch, key)
        finite = None
        if check_finite:
            # detection sits at the autodiff boundary, on the RAW grads:
            # clipping/sharding transforms below keep NaN NaN, but the
            # raw position is what mirrors the reference
            # check_finite_and_unscale op (amp/check_finite_and_unscale
            # _op.cu) and stays correct if those transforms change
            finite = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                finite &= jnp.all(jnp.isfinite(g))
        if grad_shardings is not None:
            # Pin each grad to its ZeRO layout HERE, at the autodiff
            # boundary: the batch reduction then lowers to a
            # reduce-scatter into the slot sharding. Without the pin,
            # GSPMD propagates the slot sharding backward THROUGH the
            # reduction onto the batch-sharded activation grad — a
            # batch-dim→hidden-dim transition it can only satisfy by
            # "involuntary full rematerialization" (replicate-then-slice;
            # the MULTICHIP_r03 warnings). Reference intent:
            # sharding_optimizer.py:146 "reduce rather than allreduce".
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
        if clip_global_norm is not None:
            leaves = jax.tree_util.tree_leaves(grads)
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in leaves))
            scale = jnp.minimum(1.0, clip_global_norm / (gn + 1e-6))
            grads = jax.tree_util.tree_map(
                lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                grads)
        new_params, new_state = optimizer.functional_update(
            params, grads, opt_state, lr)
        if aux:
            # functionalized running stats: new = m*old + (1-m)*batch
            # (sequentially per micro-step under grad_accum, matching
            # eager), OVERRIDING whatever zero-grad update the
            # optimizer computed for the buffer entries. check_finite's
            # keep-select below covers these too: a bad step keeps the
            # old stats along with the old params.
            for name, stat in aux.items():
                m = stat_momentum[name]
                cur = params[name].astype(jnp.float32)
                if grad_accum > 1:  # stacked [accum, C] from the scan
                    for i in range(grad_accum):
                        cur = m * cur + (1 - m) * stat[i]
                else:
                    cur = m * cur + (1 - m) * stat
                new_params[name] = cur.astype(params[name].dtype)
        if check_finite:
            # bad step → keep the incoming params/slots/step-count (the
            # reference update_loss_scaling "skip update" semantics),
            # selected on device so run-ahead dispatches after a NaN
            # step still consume good params
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o), new, old)
            new_params = keep(new_params, params)
            new_state = keep(new_state, opt_state)
            packed = jnp.stack([loss, (~finite).astype(jnp.float32)])
            return packed, new_params, new_state
        return loss, new_params, new_state

    return train_step


class ParallelEngine:
    """One-mesh hybrid-parallel compiled trainer.

    Parameters
    ----------
    model : Layer — parameters may carry ``sharding_axes`` (TP tags).
    optimizer : any optimizer with functional_init/functional_update.
    loss_fn : callable(model, batch) → scalar Tensor.
    mesh : jax Mesh; built from ``degrees`` if omitted.
    degrees : dict(dp=, mp=, pp=, sharding=, sp=) hybrid degrees.
    zero_stage : 0/1/2 shard optimizer state (and grads) over 'sharding';
        3 additionally shards params (reference sharding_optimizer.py).
    grad_accum : micro-batch accumulation count (GradientMergeOptimizer).
    train_steps_per_sync : chunk size ``step_stream`` feeds to
        ``step_many`` — k optimizer steps per dispatch (the
        DistributedStrategy knob of the same name).
    inflight_window : max un-synchronized dispatches outstanding before
        ``step``/``step_many`` block on the oldest (dispatch runs ahead
        of the device without unbounded live-buffer growth).
    check_finite : fold NaN/Inf detection into the compiled step (and
        the ``step_many`` scan body): non-finite steps skip their
        update on device, and ``step``/``step_many`` return a
        :class:`~paddle1_tpu.core.async_loss.StepFuture` whose ``.bad``
        / ``.bad_mask()`` report the flag from the same packed readback
        as the loss. The knob behind ``ResilientTrainer``'s bad-step
        policies.
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 mesh: Optional[Mesh] = None,
                 degrees: Optional[Dict[str, int]] = None,
                 zero_stage: int = 0, grad_accum: int = 1,
                 clip_global_norm: Optional[float] = None,
                 batch_spec: Optional[Any] = None,
                 donate: Optional[bool] = None,
                 amp_dtype: Optional[str] = None,
                 recompute: bool = False,
                 pp_microbatches: Optional[int] = None,
                 train_steps_per_sync: int = 1,
                 inflight_window: int = 2,
                 check_finite: bool = False):
        core_flags.maybe_enable_compilation_cache()
        # donate=None resolves from the jit_donate_params flag (the
        # reference's buffer-donation toggle) — an explicit arg wins
        donate = (bool(core_flags.flag("jit_donate_params"))
                  if donate is None else bool(donate))
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else build_mesh(
            **(degrees or {"dp": len(jax.devices())}))
        self.zero_stage = zero_stage

        # Pipeline parallelism: tag every pipelined-body sublayer so its
        # forward runs the in-graph scan+ppermute schedule over the 'pp'
        # axis (layer_transformer.TransformerEncoder._forward_pipelined).
        pp_n = int(self.mesh.shape.get("pp", 1))
        from ..nn.layer_transformer import TransformerEncoder
        if pp_n <= 1:
            # clear stale tags from a previous pp engine on the same model,
            # else _forward_pipelined would fire against the old mesh
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, TransformerEncoder):
                    sub.pipeline_axis = None
        else:
            flipped = 0
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, TransformerEncoder):
                    sub.pipeline_axis = "pp"
                    sub.pipeline_mesh = self.mesh
                    sub.pipeline_microbatches = pp_microbatches or pp_n
                    flipped += 1
            if not flipped:
                from ..core.errors import InvalidArgumentError
                raise InvalidArgumentError(
                    "pp degree > 1 needs a pipelined body "
                    "(TransformerEncoder) in the model; for arbitrary "
                    "heterogeneous stage graphs use distributed."
                    "meta_parallel.PipelineParallel (eager 1F1B schedule)")

        # Dedupe tied parameters (e.g. BERT's MLM decoder reuses the word
        # embedding): the same buffer must appear exactly once in the pjit
        # arguments (donation requires it) and receive ONE update combining
        # both gradient paths.
        sd = model.state_dict()
        seen: Dict[int, str] = {}
        self.params = {}
        for k, t in sd.items():
            if id(t) not in seen:  # aliases write back via shared Tensor
                seen[id(t)] = k
                self.params[k] = t.data
        shard_n = int(self.mesh.shape.get("sharding", 1))
        all_specs = param_partition_specs(model, zero_stage=zero_stage,
                                          zero_axis_size=shard_n)
        self.param_specs = {k: s for k, s in all_specs.items()
                            if k in self.params}
        self.opt_state = optimizer.functional_init(self.params)

        # Optimizer slots shard over 'sharding' from stage 1 up (+ the
        # param's own TP axes always apply to its slots).
        slots, step0 = self.opt_state
        self.slot_specs = {}
        for k, slot_dict in slots.items():
            base = self.param_specs.get(k, P())
            per = {}
            for sname, arr in slot_dict.items():
                if np.ndim(arr) == 0:
                    per[sname] = P()
                elif zero_stage >= 1:
                    per[sname] = zero_shard_spec(
                        base, arr.shape, zero_axis_size=shard_n)
                else:
                    per[sname] = base
            self.slot_specs[k] = per

        # Stage>=2: pin grads to the PARAM layout at the autodiff boundary
        # (see make_train_step). Left unpinned, GSPMD backward-propagates
        # the slot shardings ('sharding' on a hidden dim) through the
        # param-grad einsums onto batch-sharded activation grads — a
        # batch-dim→hidden-dim transition it can only satisfy by
        # "involuntary full rematerialization" (the MULTICHIP_r03
        # warnings: replicate-then-repartition of every activation grad).
        # Pinned to the param spec, grads materialize via a plain
        # reduction over the batch axes and the slot-sharded update
        # consumes a local slice; XLA's allreduce+slice→reduce-scatter
        # reassociation supplies the ZeRO-2 comm pattern on TPU.
        self.grad_shardings = None
        if zero_stage >= 2:
            self.grad_shardings = {
                k: NamedSharding(self.mesh, self.param_specs.get(k, P()))
                for k in self.params}

        self.batch_spec = batch_spec  # None → infer batch-dim sharding
        self.grad_accum = grad_accum
        self.check_finite = bool(check_finite)
        self._step_fn = make_train_step(model, optimizer, loss_fn,
                                        grad_accum=grad_accum,
                                        clip_global_norm=clip_global_norm,
                                        amp_dtype=amp_dtype,
                                        recompute=recompute,
                                        grad_shardings=self.grad_shardings,
                                        check_finite=self.check_finite)

        ns = lambda spec: NamedSharding(self.mesh, spec)
        param_sh = {k: ns(s) for k, s in self.param_specs.items()}
        slot_sh = ({k: {n: ns(s) for n, s in d.items()}
                    for k, d in self.slot_specs.items()}, ns(P()))
        self._param_sh, self._slot_sh = param_sh, slot_sh
        self._donate = donate

        # Dispatch/trace accounting: one dispatch per _jit/_jit_many
        # call, one trace per actual XLA recompile (the Python body of a
        # jitted fn only runs while tracing — the increment is the
        # standard trace-side-effect counter). hits = dispatches - traces
        # is the executable-cache hit count bench.py reports.
        self.dispatch_count = 0
        self.trace_count = 0
        self._seen_sigs: Dict[str, set] = {}
        self._retrace_warned = False
        # None when debug_jit_sanitizer is off: the hot path pays one
        # pointer test per dispatch, nothing else (core/locks.py idiom)
        self._jsan = jit_sanitizer.site("ParallelEngine")

        def counted_step(params, opt_state, batch, key, lr):
            self.trace_count += 1
            return self._step_fn(params, opt_state, batch, key, lr)

        self._jit = jax.jit(
            counted_step,
            in_shardings=(param_sh, slot_sh, None, None, None),
            out_shardings=(ns(P()), param_sh, slot_sh),
            donate_argnums=(0, 1) if donate else ())
        self._jit_many_cache: Dict[int, Callable] = {}

        self.train_steps_per_sync = max(int(train_steps_per_sync), 1)
        self.inflight_window = max(int(inflight_window), 1)
        self._inflight: collections.deque = collections.deque()

        # Place initial state on the mesh. The engine must OWN its param
        # buffers: with donate=True the first step donates them, and
        # device_put elides same-device copies PER SHARD — not only for
        # equivalent shardings but also e.g. single-device → replicated-
        # on-mesh, where the origin device's shard aliases the Layer's
        # own array (verified by pointer probe on the CPU sim; the PR 1
        # metadata-equivalence gate missed exactly this case and a
        # donated step deleted a live BertModel embedding out from under
        # the fluid.io registry). So copy UNCONDITIONALLY before
        # placement: one async elementwise copy per param at init, no
        # device sync (never probe buffer pointers here — that
        # serializes the async placement, PR 1's perf lesson).
        def _owned(v, sh):
            if isinstance(v, jax.Array):
                try:
                    return jax.device_put(jnp.array(v, copy=True), sh)
                except Exception:
                    pass  # exotic leaf: plain placement (donation of an
                    # alias is then possible — but nothing reached this
                    # in practice; numeric params always copy above)
            # exotic-leaf fallback; numeric params always copy above
            return jax.device_put(v, sh)  # noqa: donated-alias — see above

        self.params = {k: _owned(v, param_sh[k])
                       for k, v in self.params.items()}
        # slots/step0 come straight out of functional_init: freshly
        # allocated, nothing else holds them — aliasing is impossible
        slots = {k: {n: jax.device_put(  # noqa: donated-alias — fresh from functional_init
            a, slot_sh[0][k][n])
                     for n, a in d.items()} for k, d in slots.items()}
        self.opt_state = (slots, jax.device_put(  # noqa: donated-alias — fresh from functional_init
            step0, slot_sh[1]))

        # HBM census (ISSUE 13): tag the engine's device state so
        # obs.hbm.census() can attribute live bytes per subsystem.
        # Weakref-held — a list append, no registry touch, dies with
        # the engine (the structural-zero discipline).
        obs_hbm.register("params", self, lambda e: e.params,
                         name="ParallelEngine.params")
        obs_hbm.register("opt_state", self, lambda e: e.opt_state,
                         name="ParallelEngine.opt_state")
        # the Layer's own buffers are a separate live copy (the engine
        # copies unconditionally at init — the donation-aliasing
        # lesson); after a donate=False sync_model they alias the
        # engine's arrays, which the census dedups by buffer identity.
        # Tensor handles captured once ON THE ENGINE — state_dict()
        # per census walk would put a module sweep on the per-step
        # publish path, and capturing them in the getter closure would
        # pin the model past the weakref's lifetime
        self._obs_model_tensors = tuple(model.state_dict().values())
        obs_hbm.register(
            "params", self,
            lambda e: [t.data for t in e._obs_model_tensors],
            name="ParallelEngine.model")
        # per-signature executable cost (obs.costmodel), computed
        # lazily on the first INSTRUMENTED dispatch of each signature
        self._cost_cache: Dict[tuple, Any] = {}

    # -- data placement -----------------------------------------------------

    def shard_batch(self, batch):
        """Host batch → device arrays sharded batch-dim over (dp, sharding)."""
        multi = jax.process_count() > 1
        # multi-host: keep leaves on HOST — make_array_from_process_local_data
        # consumes numpy directly; converting to device first would buy a
        # device→host→device round-trip per leaf per step
        # multi-host leaves stay numpy (host RAM); single-host leaves go
        # through _as_arrays as before
        arrs = jax.tree_util.tree_map(
            lambda x: x if isinstance(x, jax.Array)  # pre-staged leaf
            else np.asarray(x.data if isinstance(x, Tensor) else x),
            batch, is_leaf=lambda x: isinstance(x, Tensor)) \
            if multi else _as_arrays(batch)
        spec = self.batch_spec

        def place(a):
            # pass-through for leaves that are already global jax Arrays
            # on this mesh (pre-staged batches re-fed to step): re-
            # sharding would be a no-op single-host but np.asarray on a
            # non-fully-addressable Array raises multi-host. The check is
            # mesh IDENTITY (same device array, same order), not just
            # axis-size equality (ADVICE r5): a same-shaped mesh over
            # different devices (or a different device order) must be
            # re-placed, or the step consumes misplaced data.
            if isinstance(a, jax.Array) and not isinstance(
                    a, jax.core.Tracer):
                sh = getattr(a, "sharding", None)
                m = getattr(sh, "mesh", None)
                devs = getattr(m, "devices", None)
                if m is not None and devs is not None and (
                        m is self.mesh
                        or (getattr(m, "axis_names", None)
                            == self.mesh.axis_names
                            and np.shape(devs)
                            == np.shape(self.mesh.devices)
                            and np.asarray(devs).tolist()
                            == np.asarray(self.mesh.devices).tolist())):
                    return a
                # different mesh → fall through and re-place the leaf
            s = spec if spec is not None else data_partition_spec(
                tuple(ax for ax in ("dp", "sharding")
                      if ax in self.mesh.shape))
            axes = list(s)
            if self.grad_accum > 1:
                axes = [None] + axes  # leading dim = accumulation steps
            # every leaf must carry the leading accumulation dim under
            # grad_accum (lax.scan consumes the whole batch pytree as xs,
            # scalars included) — a leaf missing it would scan the batch
            # dim or die inside scan; error at placement, where the
            # message can say so, not at jit trace time. With
            # grad_accum=1, 0-d leaves (loss weights, step counters) and
            # trailing spec axes absent from a leaf (e.g. a per-sample
            # weight without the seq dim) truncate-and-replicate.
            if (self.grad_accum > 1
                    and (a.ndim == 0 or a.shape[0] != self.grad_accum)):
                from ..core.errors import InvalidArgumentError
                raise InvalidArgumentError(
                    f"grad_accum={self.grad_accum} needs every batch leaf "
                    "shaped [grad_accum, ...] (scalars too — broadcast "
                    "them to the accumulation dim or close over them in "
                    "loss_fn); got leaf with shape "
                    f"{tuple(a.shape)}")
            axes = axes[:a.ndim]
            ndim_spec = P(*(axes + [None] * (a.ndim - len(axes))))
            sh = NamedSharding(self.mesh, ndim_spec)
            if multi and not isinstance(a, jax.Array):
                # multi-host: each process feeds its LOCAL batch shard;
                # assemble the global array over the coordination service
                # (reference: each trainer feeds its own data partition)
                return jax.make_array_from_process_local_data(sh, a)
            # numpy single-host, or a jax.Array from a DIFFERENT mesh
            # (device_put reshards global arrays on either topology)
            return jax.device_put(a, sh)  # noqa: donated-alias — batch leaves are never donated
        return jax.tree_util.tree_map(place, arrs)

    # -- training -----------------------------------------------------------

    def _shape_sig(self, tree) -> tuple:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return (str(treedef),) + tuple(
            (tuple(np.shape(l)), str(getattr(l, "dtype", type(l))))
            for l in leaves)

    def _guard_retrace(self, kind: str, batch) -> tuple:
        """Warn once when a new batch-shape signature forces a retrace
        (each retrace is a full XLA recompile — the silent host-loop
        serializer the jit_retrace_warn flag exists to surface).
        Returns the signature so instrumentation (step_cost) reuses it
        instead of re-walking the batch tree."""
        seen = self._seen_sigs.setdefault(kind, set())
        sig = self._shape_sig(batch)
        if sig in seen:
            return sig
        if self._jsan is not None:
            # sanitizer lane: the warn-once below becomes enforceable —
            # a site compiling past its signature limit raises typed
            self._jsan.note_signatures(len(seen) + 1, kind=kind)
        if seen and not self._retrace_warned \
                and core_flags.flag("jit_retrace_warn"):
            self._retrace_warned = True
            import warnings
            warnings.warn(
                f"ParallelEngine.{kind} is retracing: batch arrived with "
                f"a new shape signature (seen {len(seen)} before). Each "
                "distinct shape costs a full XLA compile — pad or bucket "
                "batches to fixed shapes (set FLAGS_jit_retrace_warn=0 "
                "to silence).")
        seen.add(sig)
        return sig

    def _push_inflight(self, fut: LossFuture) -> LossFuture:
        self._inflight.append(fut)
        while len(self._inflight) > self.inflight_window:
            # bound dispatch run-ahead: wait on (don't read back) the
            # oldest outstanding executable
            self._inflight.popleft().block()
        return fut

    # -- per-step observability (obs_metrics flag; ISSUE 10) ---------------

    @staticmethod
    def _obs_rows(batch, grad_accum: int) -> int:
        """Leading-dim sample count of one (sharded) batch — the
        samples/s numerator. Under grad_accum the leading dim is the
        accumulation axis and the per-micro-batch dim sits behind it."""
        leaves = jax.tree_util.tree_leaves(batch)
        if not leaves or np.ndim(leaves[0]) == 0:
            return 1
        shape = np.shape(leaves[0])
        if grad_accum > 1 and len(shape) > 1:
            return int(shape[0]) * int(shape[1])
        return int(shape[0])

    def step_cost(self, batch, sharded: bool = False, sig=None):
        """FLOPs + bytes of ONE optimizer step at this batch's shape
        signature (:class:`~paddle1_tpu.obs.costmodel.ExecutableCost`)
        — XLA cost analysis of the lowered train step, memoized per
        signature, labeled tree-size heuristic on failure. Called
        automatically per instrumented dispatch (``obs_metrics``,
        which hands the retrace guard's already-computed ``sig`` so
        the hot path never re-walks the batch tree); callable directly
        for on-demand attribution (bench --cost). One Python trace per
        new signature, no XLA compile."""
        if not sharded:
            batch = self.shard_batch(batch)
        if sig is None:
            sig = self._shape_sig(batch)
        c = self._cost_cache.get(sig)
        if c is None:
            ns = lambda spec: NamedSharding(self.mesh, spec)

            def lower():
                # a SEPARATE jit of the uncounted step body: lowering
                # the counted self._jit would run its trace-side-effect
                # counters and corrupt the compile accounting the
                # acceptance gates read
                return jax.jit(
                    self._step_fn,
                    in_shardings=(self._param_sh, self._slot_sh,
                                  None, None, None),
                    out_shardings=(ns(P()), self._param_sh,
                                   self._slot_sh)).lower(
                    self.params, self.opt_state, batch,
                    jax.random.key(0), jnp.asarray(0.0, jnp.float32))

            fb = obs_costmodel.tree_size_cost(
                self.params, batch=batch, extra=self.opt_state)
            c = obs_costmodel.analyze(lower, fallback=fb)
            self._cost_cache[sig] = c
        return c

    def step(self, batch,  # hot-path: one dispatch per call
             lr: Optional[float] = None) -> LossFuture:
        m = _obs_step_registry()
        if m is not None:
            _ensure_readback_observer()
        t0 = time.perf_counter() if m is not None else 0.0
        lr_val = jnp.asarray(lr if lr is not None else
                             self.optimizer.get_lr(), jnp.float32)
        with obs_trace.span("train/step", cat="Engine"):
            with obs_trace.span("train/shard", cat="Engine"):
                batch = self.shard_batch(batch)
            t1 = time.perf_counter() if m is not None else 0.0
            sig = self._guard_retrace("step", batch)
            self.dispatch_count += 1
            donated = None
            if self._jsan is not None and self._donate:
                donated = jax.tree_util.tree_leaves(
                    (self.params, self.opt_state))
                self._jsan.guard_args(donated, "step")
            with obs_trace.span("train/dispatch", cat="Engine"):
                loss, self.params, self.opt_state = self._jit(
                    self.params, self.opt_state, batch, next_key(),
                    lr_val)
            if donated is not None:
                # the old params/opt_state buffers were donated: poison
                # them so a use-after-donate (a stale alias anywhere)
                # fails deterministically instead of silently reading
                # XLA-owned storage on TPU while passing on CPU
                self._jsan.poison_donated(donated)
        if m is not None:
            t2 = time.perf_counter()
            m.histogram("train_shard_seconds").observe(t1 - t0)
            m.histogram("train_dispatch_seconds").observe(t2 - t1)
            _obs_note_steps(m, 1,
                            self._obs_rows(batch, self.grad_accum), t2,
                            cost=self.step_cost(batch, sharded=True,
                                                sig=sig))
        sched = getattr(self.optimizer, "_learning_rate", None)
        if hasattr(sched, "step"):
            sched.step()
        wrap = StepFuture if self.check_finite else LossFuture
        return self._push_inflight(wrap(loss))

    def _jit_many(self, k: int):
        fn = self._jit_many_cache.get(k)
        if fn is not None:
            return fn
        ns = lambda spec: NamedSharding(self.mesh, spec)

        def multi_step(params, opt_state, batches, keys, lrs):
            self.trace_count += 1

            def body(carry, xs):
                p, s = carry
                b, key, lr_ = xs
                loss, p, s = self._step_fn(p, s, b, key, lr_)
                return (p, s), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (batches, keys, lrs))
            return losses, params, opt_state

        fn = jax.jit(
            multi_step,
            in_shardings=(self._param_sh, self._slot_sh, None, None, None),
            out_shardings=(ns(P()), self._param_sh, self._slot_sh),
            donate_argnums=(0, 1) if self._donate else ())
        self._jit_many_cache[k] = fn
        return fn

    def step_many(self, batches: Sequence[Any],  # hot-path: k steps, one dispatch
                  lr: Optional[float] = None) -> LossFuture:
        """Run ``len(batches)`` optimizer steps inside ONE jitted
        executable (``lax.scan`` over steps, composing with the
        grad-accum inner scan): one dispatch, one donation cycle, zero
        intermediate readbacks. Returns a lazy :class:`LossFuture` over
        the ``[k]`` loss vector; the LR schedule advances k times, and
        the RNG stream consumes k keys — bit-compatible with k
        sequential ``step`` calls."""
        k = len(batches)
        if k == 0:
            from ..core.errors import InvalidArgumentError
            raise InvalidArgumentError("step_many needs >= 1 batch")
        if k == 1:
            return self.step(batches[0], lr)
        m = _obs_step_registry()
        if m is not None:
            _ensure_readback_observer()
        t0 = time.perf_counter() if m is not None else 0.0
        with obs_trace.span("train/step_many", cat="Engine",
                            args={"k": k}):
            with obs_trace.span("train/shard", cat="Engine"):
                sharded = [self.shard_batch(b) for b in batches]
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *sharded)
            t1 = time.perf_counter() if m is not None else 0.0
            sig = self._guard_retrace(f"step_many[k={k}]", sharded[0])
            sched = getattr(self.optimizer, "_learning_rate", None)
            lrs = []
            for _ in range(k):
                lrs.append(lr if lr is not None
                           else self.optimizer.get_lr())
                if hasattr(sched, "step"):
                    sched.step()
            lrs = jnp.asarray(lrs, jnp.float32)
            keys = jnp.stack([next_key() for _ in range(k)])
            self.dispatch_count += 1
            donated = None
            if self._jsan is not None and self._donate:
                donated = jax.tree_util.tree_leaves(
                    (self.params, self.opt_state))
                self._jsan.guard_args(donated, "step_many")
            with obs_trace.span("train/dispatch", cat="Engine"):
                losses, self.params, self.opt_state = self._jit_many(k)(
                    self.params, self.opt_state, stacked, keys, lrs)
            if donated is not None:
                self._jsan.poison_donated(donated)
        if m is not None:
            t2 = time.perf_counter()
            m.histogram("train_shard_seconds").observe(t1 - t0)
            m.histogram("train_dispatch_seconds").observe(t2 - t1)
            # cost of the k-step scan = k x the single-step executable
            # (same signature — the scan body IS the step fn)
            _obs_note_steps(
                m, k, self._obs_rows(sharded[0], self.grad_accum), t2,
                cost=self.step_cost(sharded[0], sharded=True, sig=sig))
        # check_finite: the scan body already emits packed [loss,
        # notfinite] pairs, so `losses` is [k, 2] and the per-step flags
        # ride the same single readback
        wrap = StepFuture if self.check_finite else LossFuture
        return self._push_inflight(wrap(losses))

    def step_stream(self, batches, lr: Optional[float] = None):
        """Drive training from any batch iterable at the engine's
        ``train_steps_per_sync`` chunk size: full chunks dispatch through
        ``step_many`` (pulling pre-staged device batches via the
        iterator's ``peek_many`` when it has one — io.DataLoader's
        buffered readers do); a short trailing chunk falls back to
        sequential ``step`` so the remainder never compiles a fresh
        scan. Yields one LossFuture per dispatch."""
        k = self.train_steps_per_sync
        it = iter(batches)
        # hot-path: the engine step loop (syncs here stall dispatch)
        with jit_sanitizer.hot_section("engine_step_loop"):
            yield from self._step_stream(it, k, lr)

    def _step_stream(self, it, k: int, lr: Optional[float]):  # hot-path
        while True:
            m = _obs_step_registry()
            t0 = time.perf_counter() if m is not None else 0.0
            if hasattr(it, "peek_many"):
                try:
                    chunk = it.peek_many(k)
                except StopIteration:
                    return
            else:
                chunk = []
                for _ in range(k):
                    try:
                        chunk.append(next(it))
                    except StopIteration:
                        break
            if m is not None:
                # host data wait: time the step loop spent blocked on
                # the input pipeline before it could even dispatch
                m.histogram("train_data_wait_seconds").observe(
                    time.perf_counter() - t0)
            if not chunk:
                return
            if len(chunk) == k and k > 1:
                yield self.step_many(chunk, lr)
            else:
                for b in chunk:
                    yield self.step(b, lr)
                if len(chunk) < k:
                    return

    def drain(self) -> None:
        """Block until every in-flight dispatched step has finished on
        device (no readback — a sync, not a fetch). Required before
        reading params for checkpointing/eval; ``sync_model``/
        ``save_checkpoint`` call it."""
        while self._inflight:
            self._inflight.popleft().block()
        jax.block_until_ready(self.params)

    def cache_stats(self) -> Dict[str, int]:
        """Executable-cache accounting: every retrace is a miss, every
        dispatch that reused a compiled executable is a hit."""
        return {"hits": self.dispatch_count - self.trace_count,
                "misses": self.trace_count}

    def sync_model(self) -> None:
        """Write engine params back into the Layer (for save/eval).
        Drains in-flight multi-step work first. With donation on, the
        Layer gets sharding-preserving COPIES — handing it the engine's
        live buffers would let the next donating step delete the
        model's tensors out from under eager code / registry saves
        (the resume-then-continue-training pattern ResilientTrainer
        relies on)."""
        self.drain()
        sd = self.model.state_dict()
        for k, arr in self.params.items():
            if k in sd:
                sd[k]._data = jnp.array(arr, copy=True) if self._donate \
                    else arr

    # -- sharded checkpoint (reference save_persistables sliced-vars
    # analog; see distributed/checkpoint.py) ---------------------------------

    def save_checkpoint(self, path: str) -> str:
        """Save params + optimizer state shard-by-shard (each process
        writes what it owns — no host gather, ZeRO-compatible). Drains
        in-flight multi-step work first."""
        self.drain()
        from . import checkpoint as dckpt
        return dckpt.save_sharded(path, {"params": self.params,
                                         "opt_state": self.opt_state})

    def load_checkpoint(self, path: str) -> None:
        """Restore directly into the engine's current shardings and push
        the weights back into the Layer."""
        from . import checkpoint as dckpt
        restored = dckpt.load_sharded(path, {"params": self.params,
                                             "opt_state": self.opt_state})
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.sync_model()

    @property
    def train_step_fn(self):
        return self._jit
